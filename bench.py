"""Flagship benchmark: GPT-2 125M training throughput, single chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: tokens/sec/chip for a full fwd+bwd+adamw step of GPT-2 125M
(bf16 compute, remat, seq 1024) — the BASELINE.json config-3 workload
("Ray Train: GPT-2 125M with XLA-collective DDP"). ``vs_baseline`` is
measured throughput over the reference's DDP envelope for this model on a
comparable-generation GPU chip (~25k tokens/s/chip for GPT-2-small DDP,
per the reference's release train tests; BASELINE.md notes the reference
stores harnesses, not absolute numbers, so this is the published
torch-DDP ballpark the ≥90%-of-NCCL target refers to).

Robustness: the remote-TPU tunnel can stall for minutes on large
compiles, so the measurement runs in a child process under a watchdog;
on timeout the config steps down (shorter model / smaller batch) and as
a last resort a CPU smoke config guarantees one JSON line.
"""

import json
import os
import subprocess
import sys
import time

REFERENCE_TOKENS_PER_SEC_PER_CHIP = 25_000.0

# (name, overrides, batch, seq, iters, warmup, timeout_s)
# "full" appears twice: on a first-attempt timeout the persistent compile
# cache usually has the executable by then, so a retry inside a smaller
# window measures without re-paying the compile.
# flash_attention="auto": XLA's fused attention at seq 1024 (measured
# ~2x the Pallas kernel's step throughput on v5e at this size); the
# Pallas kernel engages for long sequences where O(L) memory matters.
_TPU_LADDER = [
    ("full", {"flash_attention": "auto"}, 32, 1024, 10, 2, 600),
    ("full", {"flash_attention": "auto"}, 32, 1024, 10, 2, 300),
    ("small", {"n_layers": 6}, 4, 512, 6, 2, 240),
    ("tiny", {"n_layers": 2}, 2, 256, 4, 1, 120),
]

# Total wall-clock budget: rungs that don't fit in the remaining budget
# (keeping a reserve for the guaranteed CPU fallback line) are skipped
# with a recorded reason, so an outer harness timeout never kills us
# before one JSON line is printed.
_BUDGET_S = float(os.environ.get("RTPU_BENCH_BUDGET_S", "1200"))
_CPU_RESERVE_S = 270.0  # > the 240s CPU-fallback child timeout, plus slack

_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          ".jax_cache")

# Any successful TPU measurement is persisted here immediately, so a
# wedged tunnel at harness time can never erase perf evidence captured
# earlier in the round (the r03/r04 failure mode: two rounds of CPU-only
# BENCH artifacts because the one end-of-round probe hit a dead tunnel).
_LAST_GOOD_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "TPU_BENCH_LAST_GOOD.json")


def _persist_last_good(result: dict) -> None:
    extra = result.get("extra") or {}
    if extra.get("platform") in (None, "", "cpu"):
        return
    prev = _load_last_good()
    # Keep the best full-model capture; a stepped-down rung never
    # overwrites a full-model one.
    if prev is not None:
        prev_extra = prev.get("extra") or {}
        if prev_extra.get("full_model") and not extra.get("full_model"):
            return
        if (prev_extra.get("full_model") == extra.get("full_model")
                and prev.get("value", 0) >= result.get("value", 0)):
            return
    record = dict(result)
    record["extra"] = {**extra, "captured_at": time.strftime(
        "%Y-%m-%dT%H:%M:%S", time.gmtime())}
    tmp = _LAST_GOOD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2)
    os.replace(tmp, _LAST_GOOD_PATH)


def _load_last_good():
    try:
        with open(_LAST_GOOD_PATH) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _enable_compile_cache(jax):
    """Persistent XLA compilation cache so ladder rungs (and reruns of the
    same rung) don't re-pay multi-minute compiles inside the watchdog."""
    try:
        jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass  # older jax: cache is an optimization, not a requirement


def _peak_flops() -> float:
    """bf16 peak FLOP/s for the attached chip generation (device_kind
    via PJRT; the tunnel exposes a v5e = 197 TF/s bf16)."""
    import jax

    kind = ""
    try:
        kind = (jax.devices()[0].device_kind or "").lower()
    except Exception:
        pass
    table = {
        "v5e": 197e12, "v5 lite": 197e12, "v5litepod": 197e12,
        "v4": 275e12,
        "v5p": 459e12, "v5": 459e12,
        "v6e": 918e12, "trillium": 918e12,
    }
    for name, flops in table.items():
        if name in kind:
            return flops
    return 197e12  # conservative default (current tunnel chip)


def measure(mode: str) -> dict:
    import jax

    if mode == "cpu":
        # The sitecustomize hook pins the axon TPU plugin regardless of
        # JAX_PLATFORMS, so the CPU fallback must switch via jax.config
        # before first device use.
        jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache(jax)
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import GPTConfig, make_train_state, make_train_step

    # TPU-class = any non-cpu platform: the sandbox tunnel registers the
    # chip as platform "axon", not "tpu".
    on_tpu = jax.devices()[0].platform != "cpu"
    if on_tpu and mode != "cpu":
        name, overrides, batch, seq, iters, warmup, _ = next(
            lad for lad in _TPU_LADDER if lad[0] == mode)
        cfg = GPTConfig.preset("gpt2-125m", max_seq=seq, **overrides)
        full = mode == "full"
    else:  # CPU smoke mode so bench.py always produces a line
        cfg = GPTConfig.preset("gpt2-125m", n_layers=2, max_seq=256,
                               dtype=jnp.float32)
        batch, seq, iters, warmup, full = 2, 256, 3, 1, False

    opt = optax.adamw(3e-4, weight_decay=0.1)
    state = make_train_state(jax.random.key(0), cfg, opt)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=0)

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq + 1)),
                       jnp.int32)
    data = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    # Explicit compile, timed separately: populates the persistent cache
    # and keeps compile cost out of the step measurement.
    t0 = time.perf_counter()
    step = step.lower(state, data).compile()
    compile_s = round(time.perf_counter() - t0, 1)

    for _ in range(warmup):
        state, metrics = step(state, data)
        float(jax.device_get(metrics["loss"]))  # hard sync (tunnel-safe)

    # Median of per-step timings, each step synced by fetching the loss
    # scalar — robust against async-dispatch undercounting on remote
    # backends, at the cost of one scalar transfer per step.
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, metrics = step(state, data)
        float(jax.device_get(metrics["loss"]))
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))

    tokens_per_sec = batch * seq / dt
    # Model FLOPs utilization: 6*N per token (fwd+bwd). Remat recompute is
    # deliberately NOT counted — MFU compares against model FLOPs only.
    from ray_tpu.models import count_params
    n_params = count_params(state.params)
    flops_per_token = 6 * n_params
    peak = _peak_flops() if on_tpu else float("nan")
    mfu = tokens_per_sec * flops_per_token / peak if on_tpu else None

    # Stepped-down rungs measure a smaller model, so the comparison point
    # scales with model FLOPs (tokens/s ∝ 1/params under the 6N model):
    # a 2-layer rung is compared against the 2-layer-equivalent baseline,
    # not the full-model one — vs_baseline stays honest on fallback.
    full_params = 124e6
    ref_tokens = REFERENCE_TOKENS_PER_SEC_PER_CHIP * (full_params / n_params)
    return {
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec / ref_tokens, 3),
        "extra": {
            "platform": jax.devices()[0].platform,
            "device_kind": getattr(jax.devices()[0], "device_kind", ""),
            "peak_flops": peak if on_tpu else None,
            "n_params": n_params,
            "batch": batch, "seq": seq, "iters": iters,
            "step_ms": round(dt * 1e3, 2),
            "compile_s": compile_s,
            "loss": round(float(metrics["loss"]), 4),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "full_model": full,
            "mode": mode,
        },
    }


def _tail(text, n=400):
    text = (text or "").strip()
    return text[-n:] if text else ""


def _try_child(mode: str, timeout_s: int):
    """Run one measurement in a child under a watchdog.

    Returns (result_dict, None) on success or (None, reason_str) on
    failure — the reason is recorded in the artifact so a skipped rung
    is diagnosable (run_microbenchmark.py-style discipline).
    """
    # File-backed stdio: on timeout, subprocess.run's TimeoutExpired
    # carries no captured output (stderr is None on POSIX), so the child
    # writes to temp files we can always read back.
    import tempfile

    with tempfile.TemporaryFile(mode="w+") as out_f, \
            tempfile.TemporaryFile(mode="w+") as err_f:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--inner", mode],
            stdout=out_f, stderr=err_f, text=True)
        try:
            proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            err_f.seek(0)
            return None, (f"timeout after {timeout_s}s; "
                          f"stderr: {_tail(err_f.read())}")
        out_f.seek(0)
        err_f.seek(0)
        stdout, stderr = out_f.read(), err_f.read()
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except json.JSONDecodeError:
                continue
    return None, (f"rc={proc.returncode}, no JSON line; "
                  f"stderr: {_tail(stderr)}")


def probe() -> bool:
    """Cheap TPU-health check: device enumeration + one tiny matmul.
    Any non-cpu platform counts as TPU-class (the tunnel registers the
    chip as platform "axon")."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    x = jnp.ones((128, 128))
    jax.block_until_ready(x @ x)
    return d.platform != "cpu"


def _probe_once(timeout_s: int = 90):
    """One probe attempt in a child. Returns (ok, reason)."""
    try:
        probe_out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True, text=True, timeout=timeout_s)
        if probe_out.returncode == 0:
            return True, None
        return False, (f"rc={probe_out.returncode}; "
                       f"stderr: {_tail(probe_out.stderr)}")
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout_s}s (tunnel wedged)"


def _probe_with_retry(deadline: float, skipped: list) -> bool:
    """Probe the tunnel with backoff until it answers or the budget
    (minus the CPU-fallback reserve) runs out. A transiently-wedged
    tunnel often recovers within minutes; one 90 s probe (the r03/r04
    behavior) forfeits the whole round on a blip."""
    backoff = 15.0
    attempt = 0
    while True:
        attempt += 1
        ok, reason = _probe_once()
        if ok:
            return True
        skipped.append({"mode": f"probe#{attempt}", "reason": reason})
        left = deadline - time.time()
        if left < backoff + 90:
            return False
        time.sleep(backoff)
        backoff = min(backoff * 2, 120.0)


def _run_ladder(deadline: float, skipped: list):
    for mode, *_rest, timeout_s in _TPU_LADDER:
        left = deadline - time.time()
        if timeout_s > left:
            skipped.append({
                "mode": mode,
                "reason": f"skipped: {timeout_s}s rung exceeds "
                          f"{left:.0f}s remaining budget"})
            continue
        result, reason = _try_child(mode, timeout_s)
        if result is not None:
            _persist_last_good(result)
            return result
        skipped.append({"mode": mode, "reason": reason})
    return None


def capture_loop(total_s: float, interval_s: float = 120.0) -> int:
    """Opportunistic background capture: poll the tunnel for up to
    ``total_s`` seconds; the moment it answers, run the ladder and
    persist the result. Exits 0 on a persisted full-model capture."""
    deadline = time.time() + total_s
    while time.time() < deadline:
        skipped = []
        ok, reason = _probe_once()
        if ok:
            result = _run_ladder(deadline, skipped)
            if result is not None:
                print(json.dumps(result), flush=True)
                if (result.get("extra") or {}).get("full_model"):
                    return 0
        else:
            print(json.dumps({"probe": "down", "reason": reason}),
                  flush=True)
        time.sleep(interval_s)
    return 1


def main():
    if "--probe" in sys.argv:
        return 0 if probe() else 1

    if "--inner" in sys.argv:
        mode = sys.argv[sys.argv.index("--inner") + 1]
        print(json.dumps(measure(mode)))
        return 0

    if "--capture-loop" in sys.argv:
        i = sys.argv.index("--capture-loop")
        total = float(sys.argv[i + 1]) if len(sys.argv) > i + 1 else 3600.0
        return capture_loop(total)

    # The remote-TPU tunnel sometimes wedges hard (jax.devices() hangs);
    # probe (with retry/backoff inside the budget) so a dead tunnel
    # degrades to the persisted last-good TPU capture, not a CPU round.
    start = time.time()
    deadline = start + _BUDGET_S - _CPU_RESERVE_S
    skipped = []
    result = None
    if _probe_with_retry(deadline, skipped):
        result = _run_ladder(deadline, skipped)
    if result is None:
        # Tunnel never delivered a live measurement: fall back to the
        # last TPU capture persisted earlier (marked stale), and only
        # then to a CPU smoke run so one JSON line always prints.
        last_good = _load_last_good()
        if last_good is not None:
            result = last_good
            result.setdefault("extra", {})["stale"] = True
    if result is None:
        result, reason = _try_child("cpu", 240)
        if result is None:
            skipped.append({"mode": "cpu", "reason": reason})
            result = {"metric": "gpt2_125m_train_tokens_per_sec_per_chip",
                      "value": 0.0, "unit": "tokens/s/chip",
                      "vs_baseline": 0.0, "extra": {}}
    if skipped:
        result.setdefault("extra", {})["skipped"] = skipped
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
