"""Flagship benchmark: GPT-2 125M training throughput, single chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: tokens/sec/chip for a full fwd+bwd+adamw step of GPT-2 125M
(bf16 compute, remat, seq 1024) — the BASELINE.json config-3 workload
("Ray Train: GPT-2 125M with XLA-collective DDP"). ``vs_baseline`` is
measured throughput over the reference's DDP envelope for this model on a
comparable-generation GPU chip (~25k tokens/s/chip for GPT-2-small DDP,
per the reference's release train tests; BASELINE.md notes the reference
stores harnesses, not absolute numbers, so this is the published
torch-DDP ballpark the ≥90%-of-NCCL target refers to).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_TOKENS_PER_SEC_PER_CHIP = 25_000.0


def main():
    import optax

    from ray_tpu.models import GPTConfig, make_train_state, make_train_step

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = GPTConfig.preset("gpt2-125m", max_seq=1024)
        batch, seq, iters, warmup = 8, 1024, 10, 2
    else:  # CPU smoke mode so bench.py always produces a line
        cfg = GPTConfig.preset("gpt2-125m", n_layers=2, max_seq=256,
                               dtype=jnp.float32)
        batch, seq, iters, warmup = 2, 256, 3, 1

    opt = optax.adamw(3e-4, weight_decay=0.1)
    state = make_train_state(jax.random.key(0), cfg, opt)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=0)

    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq + 1)),
                       jnp.int32)
    data = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    for _ in range(warmup):
        state, metrics = step(state, data)
        float(jax.device_get(metrics["loss"]))  # hard sync (tunnel-safe)

    # Median of per-step timings, each step synced by fetching the loss
    # scalar — robust against async-dispatch undercounting on remote
    # backends, at the cost of one scalar transfer per step.
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        state, metrics = step(state, data)
        float(jax.device_get(metrics["loss"]))
        times.append(time.perf_counter() - t0)
    dt = float(np.median(times))

    tokens_per_sec = batch * seq / dt
    # Model FLOPs utilization: 6*N per token (fwd+bwd). Remat recompute is
    # deliberately NOT counted — MFU compares against model FLOPs only.
    n_params = 124e6
    flops_per_token = 6 * n_params
    peak = 275e12 if on_tpu else float("nan")  # v4 bf16 peak FLOP/s
    mfu = tokens_per_sec * flops_per_token / peak if on_tpu else None

    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(tokens_per_sec / REFERENCE_TOKENS_PER_SEC_PER_CHIP, 3),
        "extra": {
            "platform": jax.devices()[0].platform,
            "batch": batch, "seq": seq, "iters": iters,
            "step_ms": round(dt * 1e3, 2),
            "loss": round(float(metrics["loss"]), 4),
            "mfu": round(mfu, 4) if mfu is not None else None,
            "full_model": on_tpu,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
