"""Allreduce microbenchmark: xla_dist (compiled cross-process XLA
collective) vs store (object-store polling fallback).

BASELINE.json config 1 ("2-worker allreduce microbenchmark vs gloo/CPU").
Prints one JSON line per (backend, size) with effective allreduce
bandwidth: GB/s = 2*(W-1)/W * bytes / t  (ring-allreduce wire traffic).

Usage:  python benchmarks/allreduce_bench.py [--world 2] [--iters 10]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class _BenchWorker:
    def join(self, world, rank, name, backend):
        from ray_tpu.parallel import collective

        self._g = collective.init_collective_group(
            world, rank, backend=backend, group_name=name)
        return True

    def bench(self, mbytes, iters):
        n = int(mbytes * 1024 * 1024 / 4)
        x = np.ones((n,), np.float32)
        self._g.allreduce(x)  # warmup (compile/rendezvous)
        t0 = time.perf_counter()
        for _ in range(iters):
            self._g.allreduce(x)
        return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--world", type=int, default=2)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--sizes-mb", type=float, nargs="+",
                    default=[1.0, 16.0])
    args = ap.parse_args()

    import ray_tpu

    ray_tpu.init(num_cpus=args.world * 2,
                 object_store_memory=512 * 1024 * 1024)
    cls = ray_tpu.remote(_BenchWorker)
    results = []
    try:
        for backend in ("xla_dist", "store"):
            workers = [cls.remote() for _ in range(args.world)]
            ray_tpu.get([w.join.remote(args.world, r,
                                       f"arb_{backend}", backend)
                         for r, w in enumerate(workers)], timeout=180)
            for mb in args.sizes_mb:
                ts = ray_tpu.get(
                    [w.bench.remote(mb, args.iters) for w in workers],
                    timeout=600)
                t = max(ts)  # group completes when the slowest rank does
                wire = 2 * (args.world - 1) / args.world * mb / 1024
                rec = {
                    "metric": "allreduce_busbw_gbps",
                    "backend": backend,
                    "world": args.world,
                    "size_mb": mb,
                    "sec_per_op": round(t, 5),
                    "value": round(wire / t, 3),
                    "unit": "GB/s",
                }
                results.append(rec)
                print(json.dumps(rec), flush=True)
            for w in workers:
                ray_tpu.kill(w)
        if len(results) >= 4:
            xla = [r for r in results if r["backend"] == "xla_dist"][-1]
            store = [r for r in results if r["backend"] == "store"][-1]
            print(json.dumps({
                "metric": "allreduce_xla_over_store_speedup",
                "value": round(store["sec_per_op"] / xla["sec_per_op"], 2),
                "unit": "x",
            }), flush=True)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
