"""Multi-THREADED store hammer for the ThreadSanitizer pass
(``benchmarks/run_tsan_store.sh``).

Why threads, not the fork-based stress test: TSan keeps per-process
shadow memory, so racing accesses to the shared arena from *different
processes* are invisible to it — only same-process threads get
happens-before analysis. ctypes releases the GIL around every store
call, so N python threads drive store.cpp genuinely concurrently and
every lock path (robust mutex, seal/get condvar, LRU links, free-list
coalescing, the rtpu_stats_ex pin scan) runs under real contention.

Deliberately jax-free: importing jax under a libtsan LD_PRELOAD costs
minutes of instrumented interpreter time and exercises nothing in
store.cpp.

Run directly (no TSan) as a plain smoke test, or through
run_tsan_store.sh for the instrumented pass.
"""

import os
import random
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ray_tpu.object_store import plasma  # noqa: E402

_POOL = 32                  # shared id space => maximum lock contention
_CAPACITY = 512 * 1024      # tiny arena => constant eviction pressure
_THREADS = 8
_SECONDS = float(os.environ.get("TSAN_STRESS_SECONDS", "8"))


def _oid(i: int) -> bytes:
    return b"TS" + i.to_bytes(4, "little") + b"\x00" * 22


def _hammer(client: plasma.PlasmaClient, seed: int, stop: threading.Event,
            errors: list):
    rng = random.Random(seed)
    while not stop.is_set():
        o = _oid(rng.randrange(_POOL))
        r = rng.random()
        try:
            if r < 0.40:
                buf = client.create(o, rng.randrange(256, 24 * 1024))
                buf[:4] = b"data"
                del buf
                client.seal(o)
            elif r < 0.70:
                v = client.get_buffer(o, timeout_ms=rng.choice((0, 5)))
                if v is not None:
                    assert bytes(v[:4]) == b"data"
                    del v
                    client.release(o)
            elif r < 0.85:
                client.delete(o)
            elif r < 0.95:
                client.stats_ex()       # rtpu_stats + rtpu_stats_ex scan
                client.contains(o)
            else:
                client.set_allow_evict(rng.random() < 0.9)
        except (plasma.ObjectExistsError, plasma.StoreFullError):
            pass
        except OSError:
            pass                        # racing delete/evict mid-op
        except BaseException as e:      # noqa: BLE001
            errors.append(repr(e))
            return


def main() -> int:
    path = os.path.join(tempfile.mkdtemp(prefix="tsan-store-"), "arena")
    plasma.create_store(path, capacity=_CAPACITY, max_objects=256)
    client = plasma.PlasmaClient(path)
    client.set_allow_evict(True)
    stop = threading.Event()
    errors: list = []
    threads = [threading.Thread(target=_hammer,
                                args=(client, i, stop, errors), daemon=True)
               for i in range(_THREADS)]
    for t in threads:
        t.start()
    time.sleep(_SECONDS)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    stats = client.stats()
    client.close()
    os.unlink(path)
    print(f"tsan-stress: {_THREADS} threads x {_SECONDS:.0f}s, "
          f"evictions={stats['evictions']}, "
          f"live_objects={stats['num_objects']}, errors={errors}")
    if errors or any(t.is_alive() for t in threads):
        return 1
    if stats["evictions"] == 0:
        print("tsan-stress: WARNING eviction path never ran")
    return 0


if __name__ == "__main__":
    sys.exit(main())
