#!/usr/bin/env bash
# AddressSanitizer + UBSan pass over the store.cpp allocation/refcount
# paths — the memory-safety sibling of run_tsan_store.sh (which owns the
# lock paths; ISSUE 5 "extend the native-store sanitizer wiring beyond
# TSan").
#
# Rebuilds the shm store library with -fsanitize=address,undefined,
# preloads libasan/libubsan into python (the interpreter itself is
# uninstrumented, so every report points at store.cpp, not python
# internals), and drives benchmarks/tsan_store_stress.py: 8 threads in
# ONE process hammering create/seal/get/evict/delete/stats over a shared
# oid pool on a tiny arena. ASan sees heap/global/stack overflows and
# use-after-free in the store's client-side bookkeeping; UBSan catches
# misaligned arena arithmetic and integer overflow in offset math. The
# mmap'd arena ITSELF is not ASan-poisoned memory (ASan cannot redzone
# inside a shared mapping), so arena-interior overruns are TSan/stress
# territory — what this pass owns is everything on the C++ heap around
# it: per-client handles, the object table, stat structs.
#
# Leak detection is OFF: LSan would intercept the (uninstrumented)
# interpreter's allocations and drown real findings in python noise.
#
# The instrumented library is built in a temp dir and injected via
# RAY_TPU_STORE_SO (config knob `store_so`) — the tracked
# librtpu_store.so is never touched.
#
# Usage: benchmarks/run_asan_store.sh
#   TSAN_STRESS_SECONDS=30 for a longer soak (default 8; the hammer is
#   shared with the TSan harness).
# Findings are summarized on stdout and kept under $ASAN_LOG_DIR
# (default /tmp). See README "Correctness tooling" for the standing
# findings note from the last documented pass.
set -uo pipefail
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
SRC="$ROOT/ray_tpu/object_store/store.cpp"
TMPDIR_ASAN="$(mktemp -d /tmp/rtpu-asan-XXXXXX)"
SO="$TMPDIR_ASAN/librtpu_store_asan.so"
LOG="${ASAN_LOG_DIR:-/tmp}/rtpu_store_asan"
trap 'rm -rf "$TMPDIR_ASAN"' EXIT

echo "== building $(basename "$SO") with -fsanitize=address,undefined"
# Recoverable UBSan (the default): every violation logs and execution
# continues, so one report cannot mask the rest — matching
# halt_on_error=0 below; the report grep still fails the run.
g++ -O1 -g -fsanitize=address,undefined \
    -shared -fPIC -pthread -o "$SO" "$SRC" || exit 1

LIBASAN="$(g++ -print-file-name=libasan.so)"
LIBUBSAN="$(g++ -print-file-name=libubsan.so)"
rm -f "$LOG".*

echo "== driving the multithreaded store hammer under ASan+UBSan"
LD_PRELOAD="$LIBASAN $LIBUBSAN" \
RAY_TPU_STORE_SO="$SO" \
ASAN_OPTIONS="detect_leaks=0 halt_on_error=0 exitcode=0 log_path=$LOG abort_on_error=0" \
UBSAN_OPTIONS="print_stacktrace=1 halt_on_error=0 log_path=$LOG" \
python "$ROOT/benchmarks/tsan_store_stress.py" "$@"
rc=$?

echo
reports=$(cat "$LOG".* 2>/dev/null | grep -cE \
    "ERROR: AddressSanitizer|runtime error:" || true)
echo "== ASan/UBSan reports: ${reports:-0} (logs: $LOG.*)"
cat "$LOG".* 2>/dev/null | grep -A 6 -E \
    "ERROR: AddressSanitizer|runtime error:" | head -60
if [ "${reports:-0}" -gt 0 ]; then
    echo "== ASan/UBSan flagged the store: triage the logs above"
    exit 1
fi
echo "== clean pass"
exit $rc
