"""BASELINE config 2: 4-way data-parallel MLP training throughput
through JaxTrainer (fashion-MNIST-shaped synthetic data — the sandbox
has no egress, so the dataset is a deterministic stand-in with the same
shapes: 28x28 grayscale, 10 classes).

Prints one JSON line: samples/sec across the gang.
Usage: python benchmarks/mnist_dp.py [--workers 4] [--backend store]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _loop(config):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu import train
    from ray_tpu.models import MLPConfig, mlp_forward, mlp_init
    from ray_tpu.parallel import collective

    rank, ws = train.get_world_rank(), train.get_world_size()
    cfg = MLPConfig(in_dim=784, hidden=(256, 128), out_dim=10)
    params = mlp_init(jax.random.key(0), cfg)
    g = collective.get_group(
        train.session._get_session().collective_group_name) if ws > 1 \
        else None

    rng = np.random.default_rng(1234 + rank)
    bs = config["batch_size"]
    x = jnp.asarray(rng.normal(size=(bs, 784)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(bs,)))

    def loss_fn(p):
        logits = mlp_forward(p, x)
        logz = jax.nn.logsumexp(logits, axis=-1)
        return jnp.mean(logz - jnp.take_along_axis(
            logits, y[:, None], axis=1)[:, 0])

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    grad_fn(params)  # compile

    steps = config["steps"]
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = grad_fn(params)
        if g is not None:
            flat, treedef = jax.tree.flatten(grads)
            flat = [jnp.asarray(g.allreduce(np.asarray(t))) / ws
                    for t in flat]
            grads = jax.tree.unflatten(treedef, flat)
        params = jax.tree.map(lambda p, gr: p - 0.1 * gr, params, grads)
    dt = time.perf_counter() - t0
    if rank == 0:
        train.report({"samples_per_sec": steps * bs * ws / dt,
                      "step_ms": dt / steps * 1e3,
                      "loss": float(loss)})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--backend", default=None,
                    help="collective backend (default: trainer default)")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--steps", type=int, default=60)
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ray_tpu.init(num_cpus=args.workers + 1,
                 object_store_memory=256 * 1024 * 1024)
    try:
        kw = {"backend": args.backend} if args.backend else {}
        trainer = JaxTrainer(
            _loop,
            train_loop_config={"batch_size": args.batch_size,
                               "steps": args.steps},
            scaling_config=ScalingConfig(num_workers=args.workers),
            run_config=RunConfig(name="mnist_dp"),
            **kw)
        result = trainer.fit()
        assert result.ok, result.error
        m = result.metrics_history[-1]
        print(json.dumps({
            "metric": "mnist_mlp_dp_samples_per_sec",
            "value": round(m["samples_per_sec"], 1),
            "unit": "samples/s",
            "workers": args.workers,
            "step_ms": round(m["step_ms"], 2),
        }), flush=True)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
