"""Control-plane scale probe (reference envelope: BASELINE.md — 1M
queued tasks; reference mechanism: scheduling classes make the queue
O(shapes) per event, cluster_task_manager.h:42).

Measures, on one GCS process:
- sustained submission rate while queueing N INFEASIBLE tasks (they
  can never place, so this isolates queue/bookkeeping cost), plus the
  wall time for the fallback waves to finish DRAINING into the GCS
  (the submit loop is async wrt the GCS since r06; the probe waits for
  the full queue before measuring placement latency, so the latency
  metric reflects a settled 100k-deep queue, not a half-ingested one);
- placement latency of a feasible task submitted BEHIND the N queued
  ones (shape-bucketed queues make this independent of N);
- actor creation fan-out: K actors created and pinged (decentralized
  NM-local creation since SCALE_r06);
- actor CHURN: create/ping/kill cycles, A/B'd over NM-local actor
  creation (RAY_TPU_LOCAL_ACTOR_CREATION_ENABLED on vs off — the off
  mode serializes every creation through the central GCS scheduler),
  mirroring benchmarks/microbench_compare.py conventions (child
  process per mode, same probe body);
- multi-driver aggregate throughput (3 driver processes against one
  GCS).

- worker TURNAROUND: tasks/s with results actually ``get()``-ed (not
  just submitted), a small-object get-latency probe, and a plasma-put
  probe counting store objects created by sub-threshold results (0
  with the inline-return fast path on).

Prints one JSON line per metric. Run: python benchmarks/scale_bench.py
[N_tasks] [K_actors] [--gcs-out-of-process {0,1}]
[--profile-submit OUT.speedscope.json] [--drivers N]
[--submit-fastpath {0,1}] [--inline-returns {0,1}]
[--completion-fastpath {0,1}] [--worker-ring {0,1}]
[--profile-turnaround OUT.speedscope.json].

``--completion-fastpath`` pins all THREE driver-side completion
ingestion stages (RAY_TPU_COMPLETION_{ABSORB,RING,STEAL}_ENABLED) for
this run and every child driver: the SCALE_r10 A/B is two runs of this
script, 1 vs 0, same box.

``--worker-ring`` pins the worker->driver shm completion segments
(RAY_TPU_WORKER_COMPLETION_RING_ENABLED) independently of
``--completion-fastpath``: the SCALE_r11 A/B is two runs, 1 vs 0, on
top of an identical completion-ring setup, isolating the segment
transport itself.

``--inline-returns`` pins BOTH result-return fast-path stages
(RAY_TPU_WORKER_INLINE_RETURNS_ENABLED /
RAY_TPU_TASK_DONE_BATCH_ENABLED) for this run and every child driver:
the SCALE_r09 A/B is two runs of this script, 1 vs 0, same box, per
microbench_compare conventions.

``--profile-turnaround`` samples the WORKER + DRIVER sides (cluster-wide profile
fan-out) for the duration of the worker-turnaround phase and writes
the merged speedscope document (+ .folded sibling): the worker-side
evidence artifact the ISSUE 14 executor-loop shedding starts from.

``--drivers N`` sizes the multi-driver phase (default 3) so the
SCALE_r08 3-driver aggregate — and any other width — reproduces from
one command.

``--submit-fastpath`` pins ALL THREE driver submit-pipeline stages
(RAY_TPU_SUBMIT_SPEC_TEMPLATE_ENABLED / _SUBMIT_BATCH_FRAMES_ENABLED /
_SUBMIT_RING_ENABLED) for this run and every child driver: the
SCALE_r08 A/B is two runs of this script, 1 vs 0, same box, per
microbench_compare conventions.

``--profile-submit`` runs the in-process sampling profiler
(ray_tpu._private.profiler) over the DRIVER for exactly the infeasible-
queue submit phase and writes the capture as a speedscope document (+ a
.folded sibling): the evidence artifact for the SCALE_r08 attack on the
per-driver submit ceiling — it attributes the caller-thread hot path
(TaskSpec construction / arg pickling / submit flush) the next perf PR
targets.

``--gcs-out-of-process`` pins the GCS topology for the run (1 = the GCS
in its own subprocess/interpreter, 0 = in the head process — the
pre-SCALE_r07 baseline); per microbench_compare conventions the A/B is
two runs of this script, one per mode, same box. Omitted = whatever the
env/config says (default in-process).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))

# Child body for the actor-churn A/B: cycles of create-ping-kill. The
# toggle env is set by the parent per mode (microbench_compare idiom).
_CHURN_SRC = """
import json, sys, time
sys.path.insert(0, {root!r})
import ray_tpu
ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)

@ray_tpu.remote(num_cpus=0)
class Churner:
    def ping(self):
        return 1

# warm the worker pool / zygotes
warm = [Churner.remote() for _ in range(4)]
ray_tpu.get([a.ping.remote() for a in warm], timeout=120)
for a in warm:
    ray_tpu.kill(a)
time.sleep(0.5)

cycles, per_cycle = {cycles}, {per_cycle}
total = 0
t0 = time.perf_counter()
for _ in range(cycles):
    actors = [Churner.remote() for _ in range(per_cycle)]
    acks = ray_tpu.get([a.ping.remote() for a in actors], timeout=300)
    assert sum(acks) == per_cycle
    for a in actors:
        ray_tpu.kill(a)
    total += per_cycle
dt = time.perf_counter() - t0
print(json.dumps({{"churn_actors_per_s": total / dt,
                   "n": total, "wall_s": dt}}))
ray_tpu.shutdown()
"""


def _control_plane_stats(worker_mod):
    w = worker_mod.global_worker()
    return w.gcs.request("control_plane_stats", timeout=30)


def _run_churn_child(enabled: bool, cycles: int, per_cycle: int) -> dict:
    env = dict(os.environ)
    env["RAY_TPU_LOCAL_ACTOR_CREATION_ENABLED"] = "1" if enabled else "0"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    src = _CHURN_SRC.format(
        root=os.path.dirname(HERE), cycles=cycles, per_cycle=per_cycle)
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(src)
        path = f.name
    try:
        proc = subprocess.run([sys.executable, path], capture_output=True,
                              text=True, timeout=900, env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(
            f"churn child produced no result (rc={proc.returncode}): "
            f"{proc.stderr[-500:]}")
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def main():
    argv = sys.argv[1:]
    args = []
    gcs_oop = None
    profile_out = None
    profile_turnaround = None
    submit_fastpath = None
    inline_returns = None
    completion_fastpath = None
    worker_ring = None
    n_drivers = 3
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--gcs-out-of-process"):
            # Accept =VALUE, a space-separated VALUE, and the bare flag.
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv) and argv[i + 1].lower() in (
                    "0", "1", "true", "false", "on", "off"):
                i += 1
                v = argv[i]
            gcs_oop = v.strip().lower() not in ("0", "false", "off") \
                if v else True
        elif a.startswith("--submit-fastpath"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv) and argv[i + 1].lower() in (
                    "0", "1", "true", "false", "on", "off"):
                i += 1
                v = argv[i]
            submit_fastpath = v.strip().lower() not in (
                "0", "false", "off") if v else True
        elif a.startswith("--inline-returns"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv) and argv[i + 1].lower() in (
                    "0", "1", "true", "false", "on", "off"):
                i += 1
                v = argv[i]
            inline_returns = v.strip().lower() not in (
                "0", "false", "off") if v else True
        elif a.startswith("--completion-fastpath"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv) and argv[i + 1].lower() in (
                    "0", "1", "true", "false", "on", "off"):
                i += 1
                v = argv[i]
            completion_fastpath = v.strip().lower() not in (
                "0", "false", "off") if v else True
        elif a.startswith("--worker-ring"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv) and argv[i + 1].lower() in (
                    "0", "1", "true", "false", "on", "off"):
                i += 1
                v = argv[i]
            worker_ring = v.strip().lower() not in (
                "0", "false", "off") if v else True
        elif a.startswith("--profile-turnaround"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv):
                i += 1
                v = argv[i]
            profile_turnaround = v or \
                "PROFILE_worker_turnaround.speedscope.json"
        elif a.startswith("--drivers"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv):
                i += 1
                v = argv[i]
            n_drivers = max(1, int(v))
        elif a.startswith("--profile-submit"):
            _, eq, v = a.partition("=")
            if not eq and i + 1 < len(argv):
                i += 1
                v = argv[i]
            profile_out = v or "PROFILE_driver_submit.speedscope.json"
        else:
            args.append(a)
        i += 1
    n_tasks = int(args[0]) if len(args) > 0 else 100_000
    k_actors = int(args[1]) if len(args) > 1 else 200

    _SUBMIT_KNOBS = ("SUBMIT_SPEC_TEMPLATE_ENABLED",
                     "SUBMIT_BATCH_FRAMES_ENABLED", "SUBMIT_RING_ENABLED")
    if submit_fastpath is not None:
        for k in _SUBMIT_KNOBS:
            os.environ["RAY_TPU_" + k] = "1" if submit_fastpath else "0"
    _RETURN_KNOBS = ("WORKER_INLINE_RETURNS_ENABLED",
                     "TASK_DONE_BATCH_ENABLED")
    if inline_returns is not None:
        for k in _RETURN_KNOBS:
            os.environ["RAY_TPU_" + k] = "1" if inline_returns else "0"
    _COMPLETION_KNOBS = ("COMPLETION_ABSORB_ENABLED",
                         "COMPLETION_RING_ENABLED",
                         "COMPLETION_STEAL_ENABLED")
    if completion_fastpath is not None:
        for k in _COMPLETION_KNOBS:
            os.environ["RAY_TPU_" + k] = "1" if completion_fastpath else "0"
    # Worker->driver shm completion segments (ISSUE 17): pinned
    # separately from --completion-fastpath so the A/B isolates the
    # segment transport on top of an otherwise-identical ring setup.
    if worker_ring is not None:
        os.environ["RAY_TPU_WORKER_COMPLETION_RING_ENABLED"] = \
            "1" if worker_ring else "0"

    import ray_tpu
    from ray_tpu._private.config import config as _cfg

    if gcs_oop is not None:
        # Pin the topology for this process's cluster AND every child
        # driver (they inherit the env; config reads it at import).
        _cfg.set("gcs_out_of_process", gcs_oop)
        os.environ["RAY_TPU_GCS_OUT_OF_PROCESS"] = "1" if gcs_oop else "0"

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    print(json.dumps({
        "metric": "gcs_topology",
        "value": "out_of_process" if bool(_cfg.gcs_out_of_process)
        else "in_process",
        "toggle": "--gcs-out-of-process / RAY_TPU_GCS_OUT_OF_PROCESS"}),
        flush=True)
    print(json.dumps({
        "metric": "submit_fastpath",
        "value": {"template": bool(_cfg.submit_spec_template_enabled),
                  "batch_frames": bool(_cfg.submit_batch_frames_enabled),
                  "ring": bool(_cfg.submit_ring_enabled)},
        "toggle": "--submit-fastpath / RAY_TPU_SUBMIT_{SPEC_TEMPLATE,"
                  "BATCH_FRAMES,RING}_ENABLED"}), flush=True)
    print(json.dumps({
        "metric": "inline_returns",
        "value": {
            "inline": bool(_cfg.worker_inline_returns_enabled),
            "task_done_batch": bool(_cfg.task_done_batch_enabled)},
        "toggle": "--inline-returns / RAY_TPU_WORKER_INLINE_RETURNS_"
                  "ENABLED + RAY_TPU_TASK_DONE_BATCH_ENABLED"}),
        flush=True)
    print(json.dumps({
        "metric": "completion_fastpath",
        "value": {
            "absorb": bool(_cfg.completion_absorb_enabled),
            "ring": bool(_cfg.completion_ring_enabled),
            "steal": bool(_cfg.completion_steal_enabled)},
        "toggle": "--completion-fastpath / RAY_TPU_COMPLETION_"
                  "{ABSORB,RING,STEAL}_ENABLED"}), flush=True)
    print(json.dumps({
        "metric": "worker_ring",
        "value": bool(_cfg.worker_completion_ring_enabled),
        "toggle": "--worker-ring / "
                  "RAY_TPU_WORKER_COMPLETION_RING_ENABLED"}), flush=True)
    from ray_tpu._private import worker as worker_mod
    try:
        @ray_tpu.remote(resources={"impossible": 1})
        def never():
            return None

        @ray_tpu.remote
        def feasible():
            return 42

        # Warm the feasible path (lease + worker up).
        assert ray_tpu.get(feasible.remote()) == 42

        prof = None
        if profile_out:
            from ray_tpu._private.profiler import get_profiler

            prof = get_profiler()
            # Denser than the 67 Hz default: the submit phase lasts a
            # few seconds and the capture is the whole point here.
            prof_started = prof.start(hz=250)
            prof.reset()
        t0 = time.perf_counter()
        queued = [never.remote() for _ in range(n_tasks)]
        dt = time.perf_counter() - t0
        if prof is not None:
            cap = prof.collect(reset=True)
            if prof_started:
                # Leave an always-on sampler running (we only borrowed a
                # window of it); stop only the one we started.
                prof.stop()
            cap.update({"kind": "driver", "phase": "submit",
                        "bench": "scale_bench infeasible-queue submit",
                        "n_tasks": n_tasks})
            from ray_tpu._private.profiler import (
                folded_lines, speedscope_document)

            doc = speedscope_document(
                [cap], name=f"scale_bench driver submit phase "
                            f"({n_tasks} tasks, {dt:.2f}s)")
            with open(profile_out, "w") as f:
                json.dump(doc, f)
            folded_path = profile_out.rsplit(".speedscope.json", 1)[0] \
                + ".folded"
            with open(folded_path, "w") as f:
                f.write("\n".join(folded_lines([cap])) + "\n")
            print(json.dumps({
                "metric": "driver_submit_profile",
                "value": cap["samples"], "unit": "samples",
                "hz": cap["hz"], "out": profile_out,
                "folded": folded_path}), flush=True)
        # The submit loop is driver-side async: fallback waves are still
        # draining into the GCS. Barrier on the full queue so the next
        # probe measures placement behind a SETTLED n_tasks-deep queue.
        t_drain = time.perf_counter()
        deadline = time.time() + 300
        while time.time() < deadline:
            if _control_plane_stats(worker_mod)["queued_tasks"] >= n_tasks:
                break
            time.sleep(0.1)
        drain_s = time.perf_counter() - t_drain
        print(json.dumps({
            "metric": "infeasible_queue_submit_per_s",
            "value": round(n_tasks / dt, 1), "unit": "tasks/s",
            "n": n_tasks, "gcs_drain_s": round(drain_s, 2)}), flush=True)

        # Placement behind the queue: shape-bucketed scheduling means the
        # N queued infeasible tasks cost O(1) shapes per event, so this
        # stays in milliseconds regardless of N.
        lat = []
        for _ in range(20):
            t0 = time.perf_counter()
            assert ray_tpu.get(feasible.remote(), timeout=30) == 42
            lat.append(time.perf_counter() - t0)
        lat.sort()
        print(json.dumps({
            "metric": "feasible_latency_behind_queue_ms",
            "value": round(1000 * lat[len(lat) // 2], 2),
            "unit": "ms (p50)",
            "p95_ms": round(1000 * lat[int(len(lat) * 0.95)], 2),
            "queued_behind": n_tasks}), flush=True)

        del queued
        # Let the 100k-ref decref flush drain before the actor phases so
        # they measure actor-path cost, not leftover refcount churn.
        w = worker_mod.global_worker()
        deadline = time.time() + 60
        while time.time() < deadline:
            left = len(w._refs._inc_log) + len(w._refs._dec_log)
            if left == 0:
                break
            time.sleep(0.1)

        @ray_tpu.remote(num_cpus=0)
        class Pinger:
            def ping(self):
                return 1

        t0 = time.perf_counter()
        actors = [Pinger.remote() for _ in range(k_actors)]
        acks = ray_tpu.get([a.ping.remote() for a in actors], timeout=600)
        dt = time.perf_counter() - t0
        assert sum(acks) == k_actors
        print(json.dumps({
            "metric": "actor_create_and_ping_per_s",
            "value": round(k_actors / dt, 2), "unit": "actors/s",
            "n": k_actors}), flush=True)
        for a in actors:
            ray_tpu.kill(a)

        # Worker TURNAROUND: tasks/s with the results actually
        # get()-ed — the submit fast path made enqueueing nearly free
        # (SCALE_r08), so this measures the execute->complete->deliver
        # half: store puts (zero with inline returns), completion
        # framing, and the driver's wakeup path.
        w = worker_mod.global_worker()

        @ray_tpu.remote
        def nop():
            return None

        @ray_tpu.remote
        def kb():
            return b"x" * 1024

        assert ray_tpu.get(kb.remote(), timeout=60) == b"x" * 1024
        prof_thread = None
        prof_result = {}
        m_turn = 2000
        if profile_turnaround:
            from ray_tpu.experimental.state import api as state_api
            import threading as _threading

            def _capture():
                try:
                    prof_result["profiles"] = state_api.profile(
                        duration_s=6.0, hz=250)
                except Exception as e:
                    prof_result["error"] = f"{type(e).__name__}: {e}"

            prof_thread = _threading.Thread(target=_capture, daemon=True)
            prof_thread.start()
            time.sleep(0.5)   # let the windows open before the burst
            m_turn = 6000     # keep workers busy for the whole window
        puts_before = w.store.stats()["num_objects"]
        t0 = time.perf_counter()
        done = ray_tpu.get([nop.remote() for _ in range(m_turn)],
                           timeout=300)
        dt = time.perf_counter() - t0
        assert len(done) == m_turn
        lat = []
        for _ in range(40):
            t1 = time.perf_counter()
            assert len(ray_tpu.get(kb.remote(), timeout=60)) == 1024
            lat.append(time.perf_counter() - t1)
        lat.sort()
        plasma_puts = w.store.stats()["num_objects"] - puts_before
        print(json.dumps({
            "metric": "worker_turnaround_tasks_per_s",
            "value": round(m_turn / dt, 1), "unit": "tasks/s (get()-ed)",
            "n": m_turn,
            "small_get_p50_ms": round(1000 * lat[len(lat) // 2], 3),
            "small_get_p95_ms": round(
                1000 * lat[int(len(lat) * 0.95)], 3),
            "plasma_puts_observed": plasma_puts}), flush=True)
        if prof_thread is not None:
            prof_thread.join(timeout=30)
            profiles = prof_result.get("profiles") or []
            # Workers carry the execute->complete half; the driver
            # carries the ingest half (conn thread vs absorb executor
            # vs refill-send) — the SCALE_r10 completion-ingestion
            # profile needs both sides of the turnaround.
            workers_only = [p for p in profiles
                            if p.get("kind") in ("worker", "driver")]
            if workers_only:
                from ray_tpu._private.profiler import (
                    folded_lines, speedscope_document)

                doc = speedscope_document(
                    workers_only,
                    name=f"scale_bench worker turnaround phase "
                         f"({m_turn} nops, {dt:.2f}s)")
                with open(profile_turnaround, "w") as f:
                    json.dump(doc, f)
                folded_path = profile_turnaround.rsplit(
                    ".speedscope.json", 1)[0] + ".folded"
                with open(folded_path, "w") as f:
                    f.write("\n".join(folded_lines(workers_only)) + "\n")
                print(json.dumps({
                    "metric": "worker_turnaround_profile",
                    "value": sum(p.get("samples", 0)
                                 for p in workers_only),
                    "unit": "samples", "processes": len(workers_only),
                    "out": profile_turnaround,
                    "folded": folded_path}), flush=True)
            else:
                print(json.dumps({
                    "metric": "worker_turnaround_profile",
                    "value": 0,
                    "error": prof_result.get("error",
                                             "no worker profiles")}),
                    flush=True)

        # Settle: the turnaround phase above leaves THIS driver holding
        # leases on the whole shared pool; child drivers starting into
        # that pay fairness revocation + decline backoff per worker
        # (measured: first-get stalls up to ~1.9s, waves 3x slower).
        # Wait out the idle return so the multi-driver phase measures
        # multi-driver turnaround, not the lease-handoff tail.
        time.sleep(float(_cfg.lease_idle_timeout_s) + 0.5)

        # Multi-driver concurrency: D separate driver processes hammer
        # the SAME GCS with task waves (the reference's many-client
        # regime; SCALE_r04 only ever measured one driver). Reports
        # aggregate throughput and the worst per-driver p95.
        address = worker_mod.global_worker().gcs_address
        # 3000 (was 600): each child now runs long enough that steady-
        # state turnaround dominates warmup — SCALE_r08's 600-task runs
        # bounced 7.1-9.1k aggregate on identical code.
        per_driver = 3000
        child_src = f"""
import json, sys, time
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import ray_tpu
ray_tpu.init(address={address!r})
@ray_tpu.remote
def nop():
    return None
ray_tpu.get(nop.remote())   # warm a lease
lat = []
t0 = time.perf_counter()
refs = [nop.remote() for _ in range({per_driver})]
ray_tpu.get(refs, timeout=300)
dt = time.perf_counter() - t0
for _ in range(20):
    t1 = time.perf_counter()
    ray_tpu.get(nop.remote(), timeout=60)
    lat.append(time.perf_counter() - t1)
lat.sort()
print(json.dumps({{"rate": {per_driver} / dt,
                   "p95_ms": 1000 * lat[int(len(lat) * 0.95)]}}))
ray_tpu.shutdown()
"""
        with tempfile.NamedTemporaryFile("w", suffix=".py",
                                         delete=False) as f:
            f.write(child_src)
            child_path = f.name
        t0 = time.perf_counter()
        procs = []
        outs = []
        try:
            procs = [subprocess.Popen([sys.executable, child_path],
                                      stdout=subprocess.PIPE, text=True)
                     for _ in range(n_drivers)]
            for p in procs:
                try:
                    outs.append(p.communicate(timeout=600)[0])
                except subprocess.TimeoutExpired:
                    p.kill()
                    outs.append("")
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            try:
                os.unlink(child_path)
            except OSError:
                pass
        wall = time.perf_counter() - t0
        stats = []
        for o in outs:
            lines = (o or "").strip().splitlines()
            if not lines:
                continue
            try:
                stats.append(json.loads(lines[-1]))
            except json.JSONDecodeError:
                pass
        if stats:
            print(json.dumps({
                "metric": "multi_driver_task_throughput_per_s",
                "value": round(sum(s["rate"] for s in stats), 1),
                "unit": "tasks/s (aggregate)",
                "drivers": len(stats), "per_driver": per_driver,
                "worst_p95_ms": round(max(s["p95_ms"] for s in stats), 2),
                "wall_s": round(wall, 1)}), flush=True)
        else:
            print(json.dumps({
                "metric": "multi_driver_task_throughput_per_s",
                "value": 0.0, "unit": "tasks/s (aggregate)",
                "error": "all child drivers failed"}), flush=True)
    finally:
        ray_tpu.shutdown()

    # Actor churn A/B (own clusters per mode, clean state; the toggle
    # env reaches both the driver and its spawned control plane).
    cycles, per_cycle = 3, max(20, min(100, k_actors // 2))
    on = _run_churn_child(True, cycles, per_cycle)
    off = _run_churn_child(False, cycles, per_cycle)
    print(json.dumps({
        "metric": "actor_churn_per_s",
        "value": round(on["churn_actors_per_s"], 2),
        "unit": "actors/s (create+ping+kill cycles)",
        "cycles": cycles, "per_cycle": per_cycle,
        "ab": {
            "local_actor_creation_on": round(on["churn_actors_per_s"], 2),
            "local_actor_creation_off": round(off["churn_actors_per_s"], 2),
            "speedup": round(on["churn_actors_per_s"]
                             / max(off["churn_actors_per_s"], 1e-9), 2),
            "toggle": "RAY_TPU_LOCAL_ACTOR_CREATION_ENABLED",
        }}), flush=True)


if __name__ == "__main__":
    main()
