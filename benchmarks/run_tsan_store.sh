#!/usr/bin/env bash
# Documented -fsanitize=thread pass over the store.cpp lock paths
# (ROADMAP "Native store torture" open item).
#
# Rebuilds the shm store library with ThreadSanitizer, preloads libtsan
# into python (the interpreter itself is uninstrumented, so every report
# points at a store.cpp lock path, not python internals), and drives
# benchmarks/tsan_store_stress.py: 8 threads in ONE process racing
# create/seal/get/evict/delete/stats over a shared oid pool on a tiny
# arena. Threads, not the fork-based stress test, because TSan shadow
# memory is per-process — cross-process arena races are invisible to it;
# ctypes releases the GIL per call, so the threads contend for real.
# (The fork+SIGKILL robustness torture stays in
# tests/test_object_store_stress.py under the normal build.)
#
# The instrumented library is built in a temp dir and injected via
# RAY_TPU_STORE_SO — the tracked librtpu_store.so is never touched, and
# nothing else on the box can accidentally dlopen the TSan build (an
# uninstrumented process loading it dies on libtsan's static-TLS
# reservation).
#
# Usage: benchmarks/run_tsan_store.sh
#   TSAN_STRESS_SECONDS=30 for a longer soak (default 8).
# Findings are summarized on stdout and kept under $TSAN_LOG_DIR
# (default /tmp). See README "Object store" for the standing findings
# note from the last documented pass.
set -uo pipefail
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
SRC="$ROOT/ray_tpu/object_store/store.cpp"
TMPDIR_TSAN="$(mktemp -d /tmp/rtpu-tsan-XXXXXX)"
SO="$TMPDIR_TSAN/librtpu_store_tsan.so"
LOG="${TSAN_LOG_DIR:-/tmp}/rtpu_store_tsan"
trap 'rm -rf "$TMPDIR_TSAN"' EXIT

echo "== building $(basename "$SO") with -fsanitize=thread"
g++ -O1 -g -fsanitize=thread -shared -fPIC -pthread -o "$SO" "$SRC" || exit 1

LIBTSAN="$(g++ -print-file-name=libtsan.so)"
rm -f "$LOG".*

echo "== driving the multithreaded store hammer under TSan"
LD_PRELOAD="$LIBTSAN" \
RAY_TPU_STORE_SO="$SO" \
TSAN_OPTIONS="halt_on_error=0 exitcode=0 log_path=$LOG" \
python "$ROOT/benchmarks/tsan_store_stress.py" "$@"
rc=$?

echo
reports=$(cat "$LOG".* 2>/dev/null | grep -c "WARNING: ThreadSanitizer" \
    || true)
echo "== TSan reports: ${reports:-0} (logs: $LOG.*)"
cat "$LOG".* 2>/dev/null | grep -A 6 "WARNING: ThreadSanitizer" | head -60
if [ "${reports:-0}" -gt 0 ]; then
    echo "== TSan flagged the store: triage the logs above"
    exit 1
fi
echo "== clean pass"
exit $rc
