"""Lease-on vs lease-off microbenchmark comparison.

Runs benchmarks/microbench.py in child processes with the direct task
transport enabled/disabled (RAY_TPU_LEASE_ENABLED), best of N runs per
mode, and writes the artifact consumed by the round review
(MICROBENCH_r{N}.json shape). Run:

    python benchmarks/microbench_compare.py [rounds] [out.json]
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))


def run_once(lease_enabled: bool) -> dict:
    env = dict(os.environ)
    env["RAY_TPU_LEASE_ENABLED"] = "1" if lease_enabled else "0"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "microbench.py")],
        capture_output=True, text=True, timeout=900, env=env)
    out = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            out[rec["metric"]] = rec["value"]
    if not out:
        raise RuntimeError(f"microbench produced no metrics: "
                           f"{proc.stderr[-500:]}")
    return out


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    out_path = sys.argv[2] if len(sys.argv) > 2 else None
    # INTERLEAVED runs (on,off,on,off,...): box-load drift between the
    # two modes' measurement windows otherwise shows up as a phantom
    # lease regression on paths that never touch the lease manager.
    on: dict = {}
    off: dict = {}
    for _ in range(rounds):
        for best, enabled in ((on, True), (off, False)):
            run = run_once(enabled)
            for k, v in run.items():
                best[k] = max(best.get(k, 0.0), v)
    speedup = {k: round(on[k] / off[k], 2) for k in on if off.get(k)}
    result = {
        "description": f"control-plane microbenchmarks, best of {rounds}; "
                       f"direct task transport (worker leases) on vs off",
        "lease_on": on,
        "lease_off": off,
        "speedup": speedup,
    }
    text = json.dumps(result, indent=2)
    print(text)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
