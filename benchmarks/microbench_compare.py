"""A/B microbenchmark comparison for a scheduler toggle.

Runs benchmarks/microbench.py in child processes with the chosen toggle
enabled/disabled, INTERLEAVED best-of-N runs per mode, and writes the
artifact consumed by the round review (MICROBENCH_r{N}.json shape).

Toggles:
  local  (default)  RAY_TPU_LOCAL_SCHEDULING_ENABLED — node-manager
                    local-first lease grants vs the fully centralized
                    GCS scheduler
  lease             RAY_TPU_LEASE_ENABLED — direct task transport
                    (worker leases) on vs off
  device            RAY_TPU_DEVICE_OBJECTS_ENABLED — jax.Array as a
                    first-class store object (arena-staged zero-copy
                    put/get, by-reference same-process handoff) vs the
                    legacy pickle-via-host path
  profiler          RAY_TPU_PROFILER_ALWAYS_ON — the in-process
                    sampling profiler running at its default rate in
                    every process vs off (the ISSUE 12 overhead bound:
                    tasks_sync/tasks_async must stay >=0.95x with the
                    sampler on)
  submit_template   RAY_TPU_SUBMIT_SPEC_TEMPLATE_ENABLED — patch-the-
                    bytes spec templates vs per-call TaskSpec
                    construction + pickle (SCALE_r08 stage 1)
  submit_ring       RAY_TPU_SUBMIT_RING_ENABLED — shm submit ring to
                    the same-node NM vs the socket batch path
                    (SCALE_r08 stage 3)
  worker_completion_ring
                    RAY_TPU_WORKER_COMPLETION_RING_ENABLED — worker->
                    driver shm completion segments vs the socket
                    lease_tasks_done_b frames (ISSUE 17)

Run:  python benchmarks/microbench_compare.py [rounds] [out.json] [toggle]
"""

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

TOGGLES = {
    "local": ("RAY_TPU_LOCAL_SCHEDULING_ENABLED",
              "local-first node-manager scheduling (GCS spillback) on vs "
              "fully centralized GCS scheduling (off also disables the "
              "worker-lease direct transport: the baseline is the whole "
              "centralized control+data plane)"),
    "lease": ("RAY_TPU_LEASE_ENABLED",
              "direct task transport (worker leases) on vs off"),
    "device": ("RAY_TPU_DEVICE_OBJECTS_ENABLED",
               "device arrays (jax.Array) as first-class store objects — "
               "arena-staged zero-copy put/get + same-process by-reference "
               "handoff — on vs off (legacy pickle-via-host: the tensor "
               "rides in-band in the pickle stream, paying device->host->"
               "pickle->arena on put and arena->unpickle->host->device on "
               "get)"),
    "profiler": ("RAY_TPU_PROFILER_ALWAYS_ON",
                 "in-process sampling profiler running at the default "
                 "rate (profiler_hz) in every process vs off — the "
                 "overhead A/B behind the 'always-available flamegraphs' "
                 "claim; on/off >=0.95x on tasks_sync/tasks_async is "
                 "the acceptance bound"),
    "submit_template": ("RAY_TPU_SUBMIT_SPEC_TEMPLATE_ENABLED",
                        "pre-serialized TaskSpec templates — each "
                        "submission patches task id / args / timestamp "
                        "into a frozen pickled skeleton — vs per-call "
                        "TaskSpec construction + pickle.dumps"),
    "submit_ring": ("RAY_TPU_SUBMIT_RING_ENABLED",
                    "shared-memory submit ring to the same-node node "
                    "manager (classic-path dep-free submissions become "
                    "a memcpy + doorbell; the NM relays blobs to the "
                    "GCS) vs the socket submit_task_batch path"),
    "inline_returns": ("RAY_TPU_WORKER_INLINE_RETURNS_ENABLED",
                       "in-band small-object returns — sub-threshold "
                       "results skip the plasma put and ride the "
                       "completion message, backing get() straight from "
                       "the delivered blob — vs a store put per return "
                       "and a store read per get (the pre-SCALE_r09 "
                       "result-return baseline)"),
    "completion_ring": ("RAY_TPU_COMPLETION_RING_ENABLED",
                        "shared-memory completion ring from the "
                        "same-node node manager — task_done_batch "
                        "blobs absorb into the driver via memcpy + "
                        "doorbell instead of waiting on the GCS relay "
                        "— vs the socket/GCS-only delivery path"),
    "worker_completion_ring": (
        "RAY_TPU_WORKER_COMPLETION_RING_ENABLED",
        "worker->driver shm completion segments — same-node leased "
        "workers append lease completion blobs into a per-worker "
        "segment of the driver's completion ring (no socket send on "
        "the return path) — vs the lease_tasks_done_b socket frames"),
}


def run_once(env_var: str, enabled: bool) -> dict:
    env = dict(os.environ)
    env[env_var] = "1" if enabled else "0"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(HERE, "microbench.py")],
        capture_output=True, text=True, timeout=900, env=env)
    out = {}
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec.get("value"), (int, float)):
                out[rec["metric"]] = rec["value"]
    if not out:
        raise RuntimeError(f"microbench produced no metrics: "
                           f"{proc.stderr[-500:]}")
    return out


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    out_path = sys.argv[2] if len(sys.argv) > 2 else None
    toggle = sys.argv[3] if len(sys.argv) > 3 else "local"
    env_var, what = TOGGLES[toggle]
    # INTERLEAVED runs (on,off,on,off,...): box-load drift between the
    # two modes' measurement windows otherwise shows up as a phantom
    # regression on paths that never touch the scheduler.
    on: dict = {}
    off: dict = {}
    for _ in range(rounds):
        for best, enabled in ((on, True), (off, False)):
            run = run_once(env_var, enabled)
            for k, v in run.items():
                if k.endswith("_ms"):   # latency: best is the MINIMUM
                    best[k] = min(best.get(k, v), v)
                else:
                    best[k] = max(best.get(k, 0.0), v)
    # Throughput metrics only: latency (_ms) and ratio metrics have no
    # meaningful on/off quotient in this orientation.
    speedup = {k: round(on[k] / off[k], 2) for k in on
               if off.get(k) and ("per_s" in k or "gb_s" in k)}
    result = {
        "description": f"control-plane microbenchmarks, best of {rounds}; "
                       f"{what}",
        "toggle": env_var,
        f"{toggle}_on": on,
        f"{toggle}_off": off,
        "speedup": speedup,
    }
    text = json.dumps(result, indent=2)
    print(text)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
