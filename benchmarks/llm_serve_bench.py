"""BASELINE config 5 / ROADMAP serving bench: closed-loop LLM load
generator against the disaggregated serving tier.

Drives >= 1k concurrent closed-loop sessions (each session issues its
next request the moment the previous one completes) against an
autoscaled engine pool and reports:

- aggregate tokens/s
- p50/p95 TTFT (client-observed time to first streamed token)
- p50/p95 per-token latency (inter-token gap over the stream)
- the replica-count trajectory (scale-up under backlog AND scale-down
  after drain)

Sessions ride the engine's decoupled submit/collect API: one batched
``collect`` RPC per replica per tick serves every session parked there,
so client RPC rate scales with the poll rate, not the session count —
the pattern that makes 1k+ concurrent sessions drivable from one
process on the CPU test platform.

A/B: ``--mode baseline`` runs the SAME harness against a
one-request-per-call replica (the pre-engine serving shape: every
request is its own ``generate()``); ``--mode engine`` is the
continuous-batching pool. ``--mode all`` (default) runs both plus the
same-process KV-handoff probe (device-object copy counters) and the
handle-routing A/B microbench (pushed stats vs per-request stats RPCs).

On TPU hosts pin replicas to chips via ``--num-tpus-per-replica``; the
default preset is CPU-sized.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ENGINE_POOL = "llm-engine"
BASELINE_POOL = "llm-baseline"


def _engine_config(args):
    # CPU-preset model sized so DECODE IS WEIGHT-STREAMING BOUND (the
    # production LLM regime): per batch-1 token the head alone streams
    # vocab*d_model*4B = 65 MB, so one-request-per-call throughput caps
    # at memory bandwidth / 65 MB while the slotted batch amortizes the
    # stream across every occupied slot — the continuous-batching win
    # the A/B measures.
    return dict(
        preset="llama-tiny",
        model_overrides={"n_layers": args.model_layers,
                         "d_model": args.model_dim,
                         "n_heads": 8,
                         "d_ff": args.model_dim * 3,
                         "dtype": "float32"},
        max_slots=args.max_slots,
        max_len=64,
        prompt_buckets=(16,),
        max_new_tokens=32,
        max_queue=8192,
    )


def _autoscaling(args):
    from ray_tpu.serve.config import AutoscalingConfig

    return AutoscalingConfig(
        min_replicas=1, max_replicas=args.max_replicas,
        target_ongoing_requests=args.target_ongoing,
        upscale_delay_s=0.3, downscale_delay_s=1.5,
        look_back_period_s=1.5)


class _Session:
    __slots__ = ("sid", "rng", "req_id", "t_submit", "t_first", "t_prev",
                 "gaps", "tokens", "replica")

    def __init__(self, sid):
        self.sid = sid
        self.rng = random.Random(sid)
        self.req_id = None
        self.replica = None
        self.t_submit = 0.0
        self.t_first = None
        self.t_prev = None
        self.gaps = []
        self.tokens = 0

    def make_request(self, n_tokens):
        plen = self.rng.randint(4, 12)
        return {"prompt": [self.rng.randint(1, 30000) for _ in
                           range(plen)],
                "n": n_tokens, "seed": self.sid}


def _percentiles(xs, ps=(50, 95)):
    if not xs:
        return {f"p{p}": None for p in ps}
    xs = sorted(xs)
    return {f"p{p}": round(xs[min(len(xs) - 1,
                                  int(len(xs) * p / 100))], 4)
            for p in ps}


def _pool_replicas(pool):
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(ctrl.get_replicas.remote(pool), timeout=10)


def _replica_count(pool):
    from ray_tpu import serve

    # serve.status() returns {} while the controller (re)starts — never
    # assume the key exists (the old bench KeyError'd here).
    return serve.status().get(pool, {}).get("num_replicas", 0)


def run_engine_load(args):
    """Closed-loop sessions against the continuous-batching pool via
    submit + per-replica batched collect."""
    import ray_tpu

    sessions = [_Session(i) for i in range(args.sessions)]
    ttfts, per_token, latencies = [], [], []
    done_requests = 0
    total_tokens = 0
    trajectory = []

    replicas = _pool_replicas(ENGINE_POOL)
    if not replicas:
        raise RuntimeError("engine pool has no replicas")
    rr = 0

    def start_session(s, now):
        nonlocal rr
        s.replica = replicas[rr % len(replicas)]
        rr += 1
        s.t_submit = now
        s.t_first = None
        s.t_prev = None
        s.gaps = []
        s.tokens = 0
        s.req_id = None
        # Replicas are generic serve wrappers: engine methods dispatch
        # through handle_request(method, args, kwargs).
        return s.replica.handle_request.remote(
            "submit", (s.make_request(args.new_tokens),), {})

    trajectory.append(_replica_count(ENGINE_POOL))  # pre-flood floor
    now = time.perf_counter()
    pending_submit = {start_session(s, now): s for s in sessions}
    t_end = time.perf_counter() + args.duration
    t_sample = 0.0
    issuing = True

    while True:
        now = time.perf_counter()
        if now >= t_sample:
            trajectory.append(_replica_count(ENGINE_POOL))
            replicas = _pool_replicas(ENGINE_POOL) or replicas
            t_sample = now + 0.5
        if issuing and now >= t_end:
            issuing = False

        # Resolve submit acks -> request ids.
        if pending_submit:
            refs = list(pending_submit)
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=0.02)
            for ref in ready:
                s = pending_submit.pop(ref)
                try:
                    s.req_id = ray_tpu.get(ref, timeout=5)
                except Exception:
                    if issuing:   # replica died (downscale): resubmit
                        pending_submit[start_session(s, now)] = s

        # One batched collect per replica serves all its sessions.
        by_replica = {}
        for s in sessions:
            if s.req_id is not None:
                by_replica.setdefault(id(s.replica), []).append(s)
        for group in by_replica.values():
            rep = group[0].replica
            ids = [s.req_id for s in group]
            try:
                res = ray_tpu.get(
                    rep.handle_request.remote("collect", (ids,), {}),
                    timeout=10)
            except Exception:
                for s in group:   # replica died: restart the session
                    s.req_id = None
                    if issuing:
                        pending_submit[start_session(s, now)] = s
                continue
            now = time.perf_counter()
            for s in group:
                out = res.get(s.req_id) or {}
                got = out.get("tokens") or []
                if got:
                    if s.t_first is None:
                        s.t_first = now
                        ttfts.append(now - s.t_submit)
                    else:
                        gap = (now - s.t_prev) / len(got)
                        s.gaps.extend([gap] * len(got))
                    s.t_prev = now
                    s.tokens += len(got)
                if out.get("done"):
                    done_requests += 1
                    total_tokens += s.tokens
                    latencies.append(now - s.t_submit)
                    per_token.extend(s.gaps)
                    s.req_id = None
                    if issuing:
                        pending_submit[start_session(s, now)] = s

        outstanding = pending_submit or any(
            s.req_id is not None for s in sessions)
        if not issuing and not outstanding:
            break
        time.sleep(args.tick)

    wall = time.perf_counter() - (t_end - args.duration)
    # Post-drain: watch the pool scale back down.
    floor_deadline = time.time() + args.downscale_wait
    while time.time() < floor_deadline:
        n = _replica_count(ENGINE_POOL)
        trajectory.append(n)
        if n <= 1:
            break
        time.sleep(0.5)

    return {
        "metric": "llm_serve_engine",
        "mode": "continuous_batching",
        "prefix_cache": bool(args.prefix_cache),
        "sessions": args.sessions,
        "requests": done_requests,
        "tokens_per_sec": round(total_tokens / wall, 1),
        "ttft_s": _percentiles(ttfts),
        "per_token_s": _percentiles(per_token),
        "request_latency_s": _percentiles(latencies),
        "replica_trajectory": trajectory,
        "max_replicas_seen": max(trajectory or [0]),
        "scaled_up": max(trajectory or [0]) > 1,
        "scaled_down": bool(trajectory) and trajectory[-1] <= 1,
    }


def run_baseline_load(args):
    """The same closed-loop session harness against one-request-per-call
    replicas (each request is a full blocking ``generate()``)."""
    import ray_tpu
    from ray_tpu import serve

    handle = serve.get_deployment_handle(BASELINE_POOL)
    sessions = [_Session(i) for i in range(args.sessions)]
    latencies = []
    done_requests = 0
    total_tokens = 0
    trajectory = []

    def start(s, now):
        s.t_submit = now
        req = s.make_request(args.new_tokens)
        req["prompt"] += [0] * (16 - len(req["prompt"]))  # one jit shape
        return handle.remote(req).ref

    now = time.perf_counter()
    outstanding = {start(s, now): s for s in sessions}
    t_end = time.perf_counter() + args.duration
    t_sample = 0.0
    issuing = True

    while outstanding:
        now = time.perf_counter()
        if now >= t_sample:
            trajectory.append(_replica_count(BASELINE_POOL))
            t_sample = now + 0.5
        if issuing and now >= t_end:
            issuing = False
        refs = list(outstanding)
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                timeout=0.2)
        now = time.perf_counter()
        for ref in ready:
            s = outstanding.pop(ref)
            try:
                out = ray_tpu.get(ref, timeout=5)
                n_toks = len(out["tokens"])
            except Exception:
                n_toks = 0   # replica died; count nothing
            if n_toks:
                done_requests += 1
                total_tokens += n_toks
                latencies.append(now - s.t_submit)
            if issuing:
                outstanding[start(s, now)] = s

    wall = time.perf_counter() - (t_end - args.duration)
    floor_deadline = time.time() + args.downscale_wait
    while time.time() < floor_deadline:
        n = _replica_count(BASELINE_POOL)
        trajectory.append(n)
        if n <= 1:
            break
        time.sleep(0.5)

    return {
        "metric": "llm_serve_baseline",
        "mode": "one_request_per_call",
        "sessions": args.sessions,
        "requests": done_requests,
        "tokens_per_sec": round(total_tokens / wall, 1),
        # No streaming in the baseline: the first token arrives with the
        # whole response, so TTFT == request latency.
        "ttft_s": _percentiles(latencies),
        "per_token_s": _percentiles(
            [latency / args.new_tokens for latency in latencies]),
        "request_latency_s": _percentiles(latencies),
        "replica_trajectory": trajectory,
        "max_replicas_seen": max(trajectory or [0]),
    }


def run_handoff_probe(args):
    """Same-process prefill -> publish -> adopt -> decode with the
    device-object copy counters: the KV handoff must show ZERO host
    materializations (and by-reference local hits) on this platform."""
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import device_objects
    from ray_tpu.models.generate import (
        adopt_slot, decode_step, init_slotted_cache, prefill_slot,
    )
    from ray_tpu.serve.llm import EngineConfig, adopt_kv, publish_kv
    from ray_tpu.serve.llm.replicas import _build_model

    ec = EngineConfig.from_dict(_engine_config(args))
    cfg, params = _build_model(ec)
    prompt = [5, 9, 2, 11, 3]
    padded = jnp.zeros((1, 16), jnp.int32).at[:, :len(prompt)].set(
        jnp.asarray(prompt, jnp.int32))
    first, kv = prefill_slot(params, padded, jnp.int32(len(prompt)),
                             jnp.int32(0), cfg=cfg)
    jax.block_until_ready(kv)
    device_objects.reset_stats()
    t0 = time.perf_counter()
    handoff = publish_kv(kv, len(prompt), int(first[0]), n=8, seed=0)
    adopted = adopt_kv(handoff)
    handoff_ms = (time.perf_counter() - t0) * 1e3
    stats = device_objects.stats()

    # And prove the adopted cache decodes: 8 greedy tokens.
    cache = adopt_slot(init_slotted_cache(cfg, 2, ec.max_len),
                       jnp.int32(0), adopted, jnp.int32(len(prompt)))
    last = jnp.zeros((2,), jnp.int32).at[0].set(handoff["first_token"])
    active = jnp.zeros((2,), bool).at[0].set(True)
    toks = [handoff["first_token"]]
    for _ in range(7):
        nxt, cache = decode_step(params, cache, last, active,
                                 jnp.zeros((2,), jnp.int32), cfg=cfg)
        toks.append(int(nxt[0]))
        last = last.at[0].set(nxt[0])
    return {
        "metric": "llm_kv_handoff_probe",
        "host_materializations": stats["host_materializations"],
        "local_hits": stats["local_hits"],
        "rebuilds": stats["rebuilds"],
        "staged_bytes": stats["staged_bytes"],
        "handoff_ms": round(handoff_ms, 3),
        "decoded_tokens": len(toks),
        "zero_copy": stats["host_materializations"] == 0,
    }


def run_handle_ab(args):
    """Handle routing A/B: pushed per-replica loads (zero hot-path RPCs)
    vs the legacy two-stats-RPCs-per-request probe."""
    import threading

    from ray_tpu import serve
    from ray_tpu._private.config import config

    @serve.deployment(num_replicas=2, name="route-ab")
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), http_port=None)
    handle.remote(0).result(timeout=30)

    def rps(duration=3.0, threads=4):
        stop = time.perf_counter() + duration
        counts = [0] * threads

        def worker(i):
            while time.perf_counter() < stop:
                handle.remote(i).result(timeout=30)
                counts[i] += 1

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return sum(counts) / duration

    config.set("serve_handle_stats_rpc", True)
    rps_rpc = rps()
    config.set("serve_handle_stats_rpc", False)
    rps_pushed = rps()
    serve.delete("route-ab")
    return {
        "metric": "serve_handle_routing_ab",
        "rps_stats_rpc": round(rps_rpc, 1),
        "rps_pushed_stats": round(rps_pushed, 1),
        "speedup": round(rps_pushed / max(rps_rpc, 1e-9), 2),
    }


# ----------------------------------------------------------- open loop


def _proxy_ports(expect=1):
    """All per-node ingress proxy ports (one proxy per cluster node;
    ``--proxies N`` adds N-1 worker nodes so N proxies come up)."""
    import ray_tpu
    from ray_tpu.serve.api import _controller

    deadline = time.time() + 30
    while time.time() < deadline:
        ports = ray_tpu.get(_controller().proxy_addresses.remote(),
                            timeout=10)
        if len(ports) >= expect:
            return sorted(ports.values())
        time.sleep(0.3)
    raise RuntimeError(f"{expect} ingress proxies never came up")


def _tenant_prefix(tenant, n_tokens):
    """The tenant's fixed shared prompt prefix (system-prompt stand-in):
    deterministic per tenant, disjoint across tenants."""
    rng = random.Random(f"prefix:{tenant}")
    return [rng.randint(1, 30000) for _ in range(n_tokens)]


def _engine_prefix_stats():
    """Prefix-cache counters summed over the engine pool replicas."""
    import ray_tpu

    out = {"prefix_cache_hit_tokens": 0, "prefix_cache_lookup_tokens": 0,
           "prefill_tokens_computed": 0}
    try:
        for rep in _pool_replicas(ENGINE_POOL):
            st = ray_tpu.get(rep.stats.remote(), timeout=10)
            for k in out:
                out[k] += int(st.get(k) or 0)
    except Exception:
        pass
    return out


def _sse_request(port, payload, headers, rec):
    """One open-loop request over SSE; fills ``rec`` in place."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(payload).encode(),
        headers=dict({"Content-Type": "application/json"}, **headers))
    t0 = time.perf_counter()
    try:
        resp = urllib.request.urlopen(req, timeout=120)
    except urllib.error.HTTPError as e:
        rec["status"] = e.code
        rec["t_done"] = time.perf_counter() - t0
        return
    except Exception:
        rec["status"] = -1
        rec["t_done"] = time.perf_counter() - t0
        return
    rec["status"] = resp.status
    buf = b""
    t_prev = None
    try:
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                frame, buf = buf.split(b"\n\n", 1)
                for line in frame.split(b"\n"):
                    if not line.startswith(b"data: "):
                        continue
                    data = line[len(b"data: "):]
                    now = time.perf_counter()
                    if data == b"[DONE]":
                        # Explicit completion marker: an admitted (200)
                        # stream without it was LOST mid-flight — the
                        # chaos bench's zero-loss criterion keys on it.
                        rec["done"] = True
                        rec["t_done"] = now - t0
                        return
                    toks = json.loads(data)["choices"][0]["tokens"]
                    n_toks = len(toks)
                    if "token_ids" in rec:
                        rec["token_ids"].extend(int(t) for t in toks)
                    if rec.get("ttft") is None:
                        rec["ttft"] = now - t0
                    elif n_toks:
                        rec.setdefault("gaps", []).extend(
                            [(now - t_prev) / n_toks] * n_toks)
                    t_prev = now
                    rec["tokens"] = rec.get("tokens", 0) + n_toks
    except Exception:
        rec["status"] = -2
    finally:
        resp.close()
        rec.setdefault("t_done", time.perf_counter() - t0)


def run_open_loop(args):
    """Open-loop SLO bench: Poisson arrivals through the HTTP/SSE
    ingress at a RISING rate ladder, per-tenant, reporting p50/p99
    TTFT + per-token latency of ADMITTED requests and the shed rate —
    the graceful-saturation curve (shed rises past the knee; admitted
    tail latency stays bounded; no collapse)."""
    ports = _proxy_ports(expect=max(1, args.proxies))
    rng = random.Random(1234)
    tenants = [f"tenant{i}" for i in range(max(1, args.tenants))]
    shared = args.workload == "shared-prefix"
    prefixes = {t: _tenant_prefix(t, args.prefix_tokens)
                for t in tenants} if shared else {}
    prefix_stats_before = _engine_prefix_stats() if args.paged else {}
    rungs = []
    for rate in [float(r) for r in args.open_loop_rates.split(",")]:
        records = []
        threads = []
        t_end = time.perf_counter() + args.rung_duration
        i = 0
        while time.perf_counter() < t_end:
            # Poisson arrivals: exponential inter-arrival gaps.
            time.sleep(rng.expovariate(rate))
            tenant = tenants[i % len(tenants)]
            # Requests round-robin across every per-node proxy.
            port = ports[i % len(ports)]
            i += 1
            rec = {"tenant": tenant, "proxy": port, "ttft": None}
            records.append(rec)
            tail = [rng.randint(1, 200) for _ in
                    range(rng.randint(4, 12))]
            # shared-prefix: every request of a tenant opens with the
            # tenant's fixed system prompt; only the tail is unique.
            prompt = prefixes[tenant] + tail if shared else tail
            payload = {"model": "llm", "prompt": prompt,
                       "max_tokens": args.new_tokens, "stream": True,
                       "seed": i}
            th = threading.Thread(
                target=_sse_request, args=(port, payload,
                                           {"x-tenant": tenant}, rec))
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=180)
        ok = [r for r in records if r.get("status") == 200]
        shed = [r for r in records if r.get("status") in (429, 503)]
        errors = [r for r in records
                  if r.get("status") not in (200, 429, 503)]
        per_tenant = {}
        for t in tenants:
            t_ok = [r for r in ok if r["tenant"] == t]
            t_all = [r for r in records if r["tenant"] == t]
            per_tenant[t] = {
                "offered": len(t_all), "completed": len(t_ok),
                "ttft_s": _percentiles(
                    [r["ttft"] for r in t_ok if r["ttft"]],
                    ps=(50, 95, 99)),
            }
        per_proxy = {}
        for p in ports:
            p_ok = [r for r in ok if r["proxy"] == p]
            p_all = [r for r in records if r["proxy"] == p]
            p_shed = [r for r in shed if r["proxy"] == p]
            per_proxy[str(p)] = {
                "offered": len(p_all), "completed": len(p_ok),
                "shed": len(p_shed),
                "shed_rate": round(len(p_shed) / max(1, len(p_all)), 3),
                "ttft_s": _percentiles(
                    [r["ttft"] for r in p_ok if r["ttft"]],
                    ps=(50, 95, 99)),
            }
        rungs.append({
            "offered_rps": rate,
            "observed_rps": round(len(records) / args.rung_duration, 2),
            "requests": len(records),
            "completed": len(ok),
            "shed": len(shed),
            "errors": len(errors),
            "shed_rate": round(len(shed) / max(1, len(records)), 3),
            "ttft_s": _percentiles(
                [r["ttft"] for r in ok if r["ttft"] is not None],
                ps=(50, 95, 99)),
            "per_token_s": _percentiles(
                [g for r in ok for g in r.get("gaps", [])],
                ps=(50, 95, 99)),
            "request_latency_s": _percentiles(
                [r["t_done"] for r in ok if "t_done" in r],
                ps=(50, 95, 99)),
            "tokens": sum(r.get("tokens", 0) for r in ok),
            "per_tenant": per_tenant,
            "per_proxy": per_proxy,
        })
        print(json.dumps({"rung": rungs[-1]}), flush=True)
    prefix_stats = {}
    if args.paged:
        after = _engine_prefix_stats()
        prefix_stats = {k: after[k] - prefix_stats_before.get(k, 0)
                        for k in after}
    # Graceful saturation: the LAST rung must shed (we pushed past the
    # knee) while admitted p99 TTFT stays within the bound.
    admitted_p99 = [r["ttft_s"]["p99"] for r in rungs
                    if r["ttft_s"]["p99"] is not None]
    return {
        "metric": "llm_serve_open_loop",
        "engine": "paged" if args.paged else "reserved",
        "new_tokens": args.new_tokens,
        "tenants": len(tenants),
        "workload": args.workload,
        "prefix_cache": bool(args.prefix_cache),
        "prefix_tokens": args.prefix_tokens if shared else 0,
        "proxies": len(ports),
        "prefix_cache_stats": prefix_stats,
        "rungs": rungs,
        "saturation": {
            "sheds_at_peak": rungs[-1]["shed"] if rungs else 0,
            "shed_rate_curve": [r["shed_rate"] for r in rungs],
            "admitted_p99_ttft_curve": admitted_p99,
            "graceful": bool(rungs) and rungs[-1]["shed"] > 0 and
            max(admitted_p99 or [0]) <
            float(args.ttft_slo_s),
        },
    }


def run_long_context(args):
    """The memory-side unlock, measured: under ONE KV byte budget the
    reserved (max_len-reservation) engine cannot even construct — the
    typed OOM boundary — while the paged engine admits and serves a
    long context, with block-pool occupancy recorded during the run."""
    import jax

    from ray_tpu.exceptions import KVCacheExhaustedError
    from ray_tpu.serve.llm import EngineConfig, InflightBatchEngine
    from ray_tpu.serve.llm.replicas import _build_model

    max_len = args.long_context_len
    base = dict(
        preset="llama-tiny",
        model_overrides={"n_layers": 2, "d_model": 256, "n_heads": 8,
                         "d_ff": 768, "dtype": "float32",
                         "max_seq": max_len},
        max_slots=8, max_len=max_len, prompt_buckets=(16,),
        max_new_tokens=64)
    probe = EngineConfig.from_dict(base)
    per_tok = probe.kv_bytes_per_token()
    # Budget: HALF the reserved layout's up-front demand — a budget a
    # real device plausibly has. Reserved needs slots*max_len rows NOW;
    # paged only pages for live tokens.
    reserved_need = base["max_slots"] * max_len * per_tok
    budget = reserved_need // 2
    cfg, params = _build_model(probe)

    reserved_error = None
    try:
        InflightBatchEngine(params, cfg, EngineConfig.from_dict(
            dict(base, max_kv_bytes=budget)))
    except KVCacheExhaustedError as e:
        reserved_error = str(e)

    bs = 16
    nb = budget // (bs * per_tok)
    eng = InflightBatchEngine(params, cfg, EngineConfig.from_dict(
        dict(base, paged_kv=True, kv_block_size=bs,
             kv_num_blocks=int(nb), prefill_chunk=32,
             max_kv_bytes=budget)))
    occupancy = []
    stop = threading.Event()

    def sample():
        while not stop.is_set():
            occupancy.append(eng.stats()["kv_block_occupancy"])
            time.sleep(0.05)

    sampler = threading.Thread(target=sample, daemon=True)
    sampler.start()
    prompt = [1 + (i % 200) for i in range(args.long_context_prompt)]
    t0 = time.perf_counter()
    out = eng.generate(prompt, 48)
    wall = time.perf_counter() - t0
    stop.set()
    sampler.join(timeout=5)
    stats = eng.stats()
    eng.stop()
    return {
        "metric": "llm_long_context_paged_vs_reserved",
        "kv_budget_bytes": int(budget),
        "reserved_need_bytes": int(reserved_need),
        "reserved_oom": reserved_error is not None,
        "reserved_error": reserved_error,
        "paged_prompt_len": len(prompt),
        "paged_tokens_out": len(out),
        "paged_wall_s": round(wall, 2),
        "kv_block_occupancy_peak": max(occupancy or [0]),
        "kv_blocks_total": stats["kv_blocks_total"],
        "no_block_leak": stats["kv_blocks_used"] == 0,
    }


def _fault_stats():
    import ray_tpu
    from ray_tpu.serve.api import _controller

    return ray_tpu.get(_controller().fault_stats.remote(), timeout=30)


def _router_migrations(name):
    """Sum ``request_migrations_total`` over the router deployment's
    replicas — engine/decode deaths resubmit inside the ROUTER process,
    so that is where the tally lives."""
    import ray_tpu

    total = 0
    try:
        reps = _pool_replicas(name)
    except Exception:
        return 0
    for rep in reps:
        try:
            st = ray_tpu.get(rep.stats.remote(), timeout=10)
            total += int(st.get("request_migrations_total") or 0)
        except Exception:
            continue
    return total


def _kill_one_replica(pool, killed, kills, t0, require_busy=True):
    """SIGKILL one live replica process of ``pool``. With
    ``require_busy`` only a replica with in-flight work is eligible —
    killing an idle spare proves nothing about migration."""
    import signal

    import ray_tpu

    try:
        reps = _pool_replicas(pool)
    except Exception:
        return False
    stats = []
    for rep in reps:
        try:
            stats.append(ray_tpu.get(rep.stats.remote(), timeout=10))
        except Exception:
            continue
    stats = [s for s in stats
             if s.get("pid") and s["pid"] not in killed]
    if require_busy:
        stats = [s for s in stats if int(s.get("ongoing") or 0) > 0]
    if not stats:
        return False
    stats.sort(key=lambda s: -int(s.get("ongoing") or 0))
    pid = int(stats[0]["pid"])
    try:
        os.kill(pid, signal.SIGKILL)
    except OSError:
        return False
    killed.add(pid)
    kills.append({"pool": pool, "pid": pid,
                  "ongoing_at_kill": int(stats[0].get("ongoing") or 0),
                  "t_s": round(time.perf_counter() - t0, 2)})
    return True


def run_chaos(args):
    """Crash-transparency proof: SIGKILL engine/decode replicas under
    open SSE load, in BOTH serving modes. Criteria (asserted):

    - zero lost admitted requests — every stream the proxy answered
      with 200 reaches ``[DONE]``; sheds (429/503) are allowed, silent
      truncation is not;
    - bit-identical resume — deterministic greedy probe prompts,
      referenced before any chaos, stream back the exact same token
      ids THROUGH the migrations (no duplicate, no gap);
    - migrations observed (router ``request_migrations_total`` > 0) and
      every kill detected + replaced by the controller
      (``serve_replica_restarts_total`` delta, ``time_to_replace_s``
      recorded per replacement — the satellite-f histogram);
    - combined mode additionally redeploys the app mid-load: the
      controller drains the old generation (``drain_duration_s``
      entries appear) and no in-flight request fails.
    """
    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    port = _proxy_ports()[0]
    n_kills = max(2, args.chaos_kills)
    modes_out = []
    for mode in ("combined", "disaggregated"):
        # Wider prompt buckets than the open-loop default: a migrated
        # stream re-prefills prompt+generated, which must fit a bucket.
        ecfg = dict(_engine_config(args), max_queue=256,
                    prompt_buckets=(16, 32, 64),
                    max_new_tokens=max(32, args.new_tokens))
        pool = ENGINE_POOL if mode == "combined" else "llm-decode"
        if mode == "combined":
            app = build_llm_app(ecfg, mode="combined", name="llm",
                                autoscaling_config=None, num_replicas=2)
        else:
            app = build_llm_app(ecfg, mode="disaggregated", name="llm",
                                num_prefill_replicas=1,
                                num_decode_replicas=2)
        serve.run(app, route_prefix="/llm").remote(
            {"prompt": [1, 2, 3], "n": args.new_tokens}).result(
                timeout=600)
        fs0 = _fault_stats()
        mig0 = _router_migrations("llm")

        # Deterministic greedy references, recorded BEFORE any chaos.
        probe_prompts = {"probe-a": [5, 9, 2, 11, 3],
                         "probe-b": [17, 4, 8, 1, 13, 6]}
        refs = {}
        for pname, prompt in probe_prompts.items():
            rec = {"ttft": None, "token_ids": []}
            _sse_request(port, {"model": "llm", "prompt": prompt,
                                "max_tokens": args.new_tokens,
                                "stream": True}, {}, rec)
            if rec.get("status") != 200 or not rec.get("done"):
                raise RuntimeError(f"chaos reference stream failed: "
                                   f"{rec}")
            refs[pname] = list(rec["token_ids"])

        records = []
        t0 = time.perf_counter()
        stop_at = t0 + args.chaos_duration

        def probe_loop(pname):
            prompt = probe_prompts[pname]
            while time.perf_counter() < stop_at:
                rec = {"kind": "probe", "probe": pname, "ttft": None,
                       "token_ids": []}
                records.append(rec)
                _sse_request(port, {"model": "llm", "prompt": prompt,
                                    "max_tokens": args.new_tokens,
                                    "stream": True}, {}, rec)

        def load_loop(i):
            r = random.Random(1000 + i)
            while time.perf_counter() < stop_at:
                prompt = [r.randint(1, 30000)
                          for _ in range(r.randint(4, 12))]
                rec = {"kind": "load", "ttft": None}
                records.append(rec)
                _sse_request(port, {"model": "llm", "prompt": prompt,
                                    "max_tokens": args.new_tokens,
                                    "stream": True, "seed": i}, {}, rec)
                time.sleep(r.expovariate(8.0))

        threads = [threading.Thread(target=probe_loop, args=(p,))
                   for p in probe_prompts]
        threads += [threading.Thread(target=load_loop, args=(i,))
                    for i in range(2)]
        for th in threads:
            th.start()

        kills = []
        killed = set()
        for _ in range(n_kills):
            # Space the kills so later ones can land on the REPLACEMENT
            # the controller spawned for the earlier ones.
            time.sleep(args.chaos_duration / (n_kills + 1))
            deadline = time.perf_counter() + 30
            while time.perf_counter() < deadline:
                # Insist on a busy victim until the last 5 s of the
                # window, then take any live replica.
                busy_only = time.perf_counter() < deadline - 5
                if _kill_one_replica(pool, killed, kills, t0,
                                     require_busy=busy_only):
                    break
                time.sleep(0.25)
        for th in threads:
            th.join(timeout=300)

        # Settle until the controller has detected every kill AND
        # closed the replacement loop (time_to_replace per kill).
        fs1 = _fault_stats()
        deadline = time.time() + 120
        while time.time() < deadline:
            fs1 = _fault_stats()
            if (fs1["replica_restarts_total"] -
                    fs0["replica_restarts_total"]) >= len(kills) and \
                    len(fs1["time_to_replace_s"]) >= \
                    len(fs0["time_to_replace_s"]) + len(kills):
                break
            time.sleep(1.0)

        admitted = [r for r in records if r.get("status") == 200]
        lost = [r for r in admitted if not r.get("done")]
        broken = [r for r in records
                  if r.get("status") in (-1, -2)]
        shed = [r for r in records if r.get("status") in (429, 503)]
        probes = [r for r in admitted
                  if r.get("kind") == "probe" and r.get("done")]
        mismatched = [r for r in probes
                      if r["token_ids"] != refs[r["probe"]]]
        migrations = _router_migrations("llm") - mig0
        restarts = (fs1["replica_restarts_total"] -
                    fs0["replica_restarts_total"])
        t_replace = [round(x, 3) for x in
                     fs1["time_to_replace_s"]
                     [len(fs0["time_to_replace_s"]):]]
        out = {
            "mode": mode,
            "kills": kills,
            "requests": len(records),
            "admitted": len(admitted),
            "shed": len(shed),
            "transport_errors": len(broken),
            "lost_admitted": len(lost),
            "probe_streams": len(probes),
            "probe_mismatches": len(mismatched),
            "migrations_total": migrations,
            "replica_restarts": restarts,
            "time_to_replace_s": t_replace,
            "ttft_s": _percentiles(
                [r["ttft"] for r in admitted if r.get("ttft")],
                ps=(50, 95, 99)),
            "ttft_max_s": round(max(
                [r["ttft"] for r in admitted if r.get("ttft")] or [0]),
                3),
            "zero_admitted_lost": not lost and not broken,
            "bit_identical": bool(probes) and not mismatched,
        }

        if mode == "combined":
            # Rolling restart THROUGH the drain path: redeploy the same
            # app mid-load; every old replica is drained (not killed
            # cold) and no in-flight request fails.
            drain0 = list(fs1.get("drain_duration_s") or [])
            rec2 = []
            stop2 = time.perf_counter() + 8.0

            def redeploy_load(i):
                r = random.Random(2000 + i)
                while time.perf_counter() < stop2:
                    prompt = [r.randint(1, 30000)
                              for _ in range(r.randint(4, 12))]
                    rec = {"ttft": None}
                    rec2.append(rec)
                    _sse_request(port, {"model": "llm",
                                        "prompt": prompt,
                                        "max_tokens": args.new_tokens,
                                        "stream": True}, {}, rec)
                    time.sleep(r.expovariate(8.0))

            ths2 = [threading.Thread(target=redeploy_load, args=(i,))
                    for i in range(2)]
            for th in ths2:
                th.start()
            time.sleep(1.0)
            serve.run(app, route_prefix="/llm")
            for th in ths2:
                th.join(timeout=300)
            deadline = time.time() + 60
            drains = []
            while time.time() < deadline:
                drains = list(_fault_stats().get(
                    "drain_duration_s") or [])[len(drain0):]
                if len(drains) >= 2:
                    break
                time.sleep(1.0)
            adm2 = [r for r in rec2 if r.get("status") == 200]
            lost2 = [r for r in adm2 if not r.get("done")] + \
                [r for r in rec2 if r.get("status") in (-1, -2)]
            out["redeploy"] = {
                "requests": len(rec2),
                "admitted": len(adm2),
                "lost_admitted": len(lost2),
                "drained_replicas": len(drains),
                "drain_duration_s": [round(d, 3) for d in drains],
            }
            assert not lost2, (
                f"redeploy lost {len(lost2)} in-flight requests")
            assert len(drains) >= 2, (
                f"redeploy drained {len(drains)} replicas, wanted >=2")

        print(json.dumps({"chaos": out}), flush=True)
        serve.delete("llm")
        if mode == "combined":
            serve.delete(ENGINE_POOL)
        else:
            serve.delete("llm-prefill")
            serve.delete("llm-decode")

        assert len(kills) >= 2, f"only {len(kills)} kills landed"
        assert out["zero_admitted_lost"], (
            f"lost admitted requests: {len(lost)} incomplete, "
            f"{len(broken)} transport errors")
        assert out["bit_identical"], (
            f"{len(mismatched)}/{len(probes)} probe streams diverged "
            "from the pre-chaos greedy reference")
        assert migrations >= 1, "no request migration was observed"
        assert restarts >= len(kills), (
            f"controller detected {restarts} deaths for "
            f"{len(kills)} kills")
        assert len(t_replace) >= len(kills), (
            f"time_to_replace recorded {len(t_replace)} replacements "
            f"for {len(kills)} kills")
        modes_out.append(out)

    return {"metric": "llm_serve_chaos",
            "new_tokens": args.new_tokens,
            "kills_per_mode": n_kills,
            "chaos_duration_s": args.chaos_duration,
            "modes": modes_out}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="all",
                    choices=["all", "engine", "baseline", "probe",
                             "handle-ab", "open-loop", "long-context",
                             "chaos"])
    ap.add_argument("--sessions", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=15.0,
                    help="load-phase seconds per mode")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--max-slots", type=int, default=32)
    ap.add_argument("--model-dim", type=int, default=512)
    ap.add_argument("--model-layers", type=int, default=4)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--target-ongoing", type=float, default=32.0,
                    help="autoscaler target load per engine replica")
    ap.add_argument("--tick", type=float, default=0.025,
                    help="collect poll period (s)")
    ap.add_argument("--downscale-wait", type=float, default=45.0)
    ap.add_argument("--baseline-static-replicas", type=int, default=3,
                    help="pre-grant the one-call baseline this many "
                         "static replicas (0 = autoscaled like the "
                         "engine pool)")
    ap.add_argument("--num-tpus-per-replica", type=int, default=0)
    # --- open-loop SLO bench -------------------------------------------
    ap.add_argument("--open-loop-rates", default="2,4,8,16,32,64",
                    help="rising offered-rate ladder (requests/s)")
    ap.add_argument("--rung-duration", type=float, default=10.0)
    ap.add_argument("--tenants", type=int, default=4)
    ap.add_argument("--proxies", type=int, default=1,
                    help="per-node ingress proxies to drive: N > 1 "
                         "brings up an N-node cluster (one proxy per "
                         "node) and round-robins the open-loop load "
                         "across them, with a per-proxy shed/TTFT "
                         "breakdown in the rung output")
    ap.add_argument("--workload", default="random",
                    choices=["random", "shared-prefix"],
                    help="shared-prefix: every tenant's requests open "
                         "with the tenant's fixed system prompt "
                         "(--prefix-tokens) plus a unique tail — the "
                         "prefix-cache target workload")
    ap.add_argument("--prefix-tokens", type=int, default=48,
                    help="shared system-prompt length per tenant "
                         "(shared-prefix workload)")
    ap.add_argument("--prefix-cache", type=int, default=None,
                    choices=[0, 1],
                    help="A/B toggle: run the paged engine with the "
                         "prefix cache on (1) or off (0); unset keeps "
                         "the pre-ISSUE-18 default (off)")
    ap.add_argument("--paged", action="store_true", default=True)
    ap.add_argument("--no-paged", dest="paged", action="store_false",
                    help="A/B: reserved max_len KV instead of paged")
    ap.add_argument("--ttft-slo-s", type=float, default=5.0,
                    help="admitted-request p99 TTFT bound for the "
                         "graceful-saturation verdict")
    ap.add_argument("--http-port", type=int, default=18640)
    # --- chaos (fault-tolerance) bench ---------------------------------
    ap.add_argument("--chaos-duration", type=float, default=20.0,
                    help="seconds of SSE load per serving mode during "
                         "which replicas are SIGKILLed")
    ap.add_argument("--chaos-kills", type=int, default=2,
                    help="replica SIGKILLs per serving mode (min 2)")
    ap.add_argument("--long-context-len", type=int, default=1024)
    ap.add_argument("--long-context-prompt", type=int, default=700)
    ap.add_argument("--out", default="",
                    help="write all result records to this JSON file")
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    open_loop = args.mode in ("all", "open-loop")
    http_needed = open_loop or args.mode == "chaos"
    ingress_cfg = {
        # Admit roughly what the engine can HOLD at
        # bounded TTFT (slots + ~1 wave of queue); streams
        # each occupy one pump thread for their life, so
        # the executor must cover max_inflight.
        "serve_ingress_max_inflight": 40,
        "serve_ingress_queue_watermark": 16,
        "serve_ingress_queue_timeout_s": 1.5,
        "serve_ingress_executor_threads": 64,
    } if http_needed else None
    cluster = None
    if args.proxies > 1:
        # One ingress proxy per node: an N-proxy front door needs an
        # N-node cluster underneath it.
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 8})
        for _ in range(args.proxies - 1):
            cluster.add_node(num_cpus=4)
        cluster.connect(object_store_memory=512 * 1024 * 1024,
                        _system_config=ingress_cfg)
        cluster.wait_for_nodes()
    else:
        ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024,
                     _system_config=ingress_cfg)
    serve.start(http_port=args.http_port if http_needed else None)
    results = []
    opts = {"num_tpus": args.num_tpus_per_replica} \
        if args.num_tpus_per_replica else None
    try:
        if args.mode == "chaos":
            results.append(run_chaos(args))
            print(json.dumps(results[-1]), flush=True)

        if args.mode in ("all", "long-context"):
            results.append(run_long_context(args))
            print(json.dumps(results[-1]), flush=True)

        if open_loop:
            ecfg = dict(_engine_config(args),
                        max_queue=256)
            if args.paged:
                ecfg.update(paged_kv=True, kv_block_size=16,
                            prefill_chunk=16,
                            prefix_cache_enabled=bool(args.prefix_cache))
            if args.workload == "shared-prefix":
                # Room for the system prompt + tail + decode; paged
                # admission ignores prompt_buckets.
                ecfg.update(max_len=max(
                    ecfg["max_len"],
                    args.prefix_tokens + 16 + args.new_tokens + 16))
            handle = serve.run(
                build_llm_app(ecfg, mode="combined", name="llm",
                              autoscaling_config=None,
                              num_replicas=1,
                              ray_actor_options=opts),
                route_prefix="/llm")
            handle.remote({"prompt": [1, 2, 3],
                           "n": args.new_tokens}).result(timeout=600)
            results.append(run_open_loop(args))
            print(json.dumps(results[-1]), flush=True)
            serve.delete("llm")
            serve.delete(ENGINE_POOL)
        if args.mode in ("all", "probe"):
            results.append(run_handoff_probe(args))
            print(json.dumps(results[-1]), flush=True)

        if args.mode in ("all", "engine"):
            ecfg = _engine_config(args)
            if args.prefix_cache is not None:
                # Closed-loop prefix-cache A/B rides the paged engine
                # (the cache only exists over the block pool).
                ecfg.update(paged_kv=True, kv_block_size=16,
                            prefill_chunk=16,
                            prefix_cache_enabled=bool(args.prefix_cache))
            handle = serve.run(
                build_llm_app(ecfg, mode="combined",
                              name="llm",
                              autoscaling_config=_autoscaling(args),
                              ray_actor_options=opts),
                route_prefix="/llm")
            handle.remote({"prompt": [1, 2, 3],
                           "n": args.new_tokens}).result(timeout=600)
            results.append(run_engine_load(args))
            print(json.dumps(results[-1]), flush=True)
            serve.delete("llm")
            serve.delete(ENGINE_POOL)

        if args.mode in ("all", "baseline"):
            from ray_tpu.serve.llm.replicas import normalize_request

            ecfg = _engine_config(args)

            # The blocking one-call-per-request shape starves the
            # controller's stats probes under load (every actor thread
            # is parked in generate()), so its autoscaler rarely fires —
            # itself a finding. --baseline-static-replicas N grants the
            # baseline the engine pool's PEAK capacity up front instead,
            # the strongest version of the comparison.
            static_n = args.baseline_static_replicas
            @serve.deployment(
                name=BASELINE_POOL, max_ongoing_requests=64,
                num_replicas=static_n or 1,
                autoscaling_config=None if static_n
                else _autoscaling(args),
                ray_actor_options=opts or {})
            class OneCallLLM:
                """Pre-engine shape: every request runs its own
                ``generate()`` — no batching across requests."""

                def __init__(self):
                    import jax as _jax

                    from ray_tpu.serve.llm import EngineConfig
                    from ray_tpu.serve.llm.replicas import _build_model

                    self._jax = _jax
                    ec = EngineConfig.from_dict(ecfg)
                    self.cfg, self.params = _build_model(ec)

                def __call__(self, request):
                    import jax.numpy as _jnp

                    from ray_tpu.models.generate import generate

                    req = normalize_request(request)
                    out = generate(
                        self.params,
                        _jnp.asarray([req["prompt"]], _jnp.int32),
                        self._jax.random.key(req["seed"]),
                        cfg=self.cfg, max_new_tokens=req["n"] or 16,
                        temperature=0.0)
                    return {"tokens": [int(t) for t in out[0]]}

            handle = serve.run(OneCallLLM.bind(), http_port=None)
            handle.remote({"prompt": [1, 2, 3] + [0] * 13,
                           "n": args.new_tokens}).result(timeout=600)
            results.append(run_baseline_load(args))
            print(json.dumps(results[-1]), flush=True)
            serve.delete(BASELINE_POOL)

        if args.mode in ("all", "handle-ab"):
            results.append(run_handle_ab(args))
            print(json.dumps(results[-1]), flush=True)

        eng = next((r for r in results
                    if r["metric"] == "llm_serve_engine"), None)
        base = next((r for r in results
                     if r["metric"] == "llm_serve_baseline"), None)
        if eng and base:
            print(json.dumps({
                "metric": "llm_serve_ab_summary",
                "engine_tokens_per_sec": eng["tokens_per_sec"],
                "baseline_tokens_per_sec": base["tokens_per_sec"],
                "speedup": round(eng["tokens_per_sec"] /
                                 max(base["tokens_per_sec"], 1e-9), 2),
            }), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"bench": "llm_serve", "results": results},
                          f, indent=1)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()
        if cluster is not None:
            cluster.shutdown()


if __name__ == "__main__":
    main()
