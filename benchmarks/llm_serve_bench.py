"""BASELINE config 5 / ROADMAP serving bench: closed-loop LLM load
generator against the disaggregated serving tier.

Drives >= 1k concurrent closed-loop sessions (each session issues its
next request the moment the previous one completes) against an
autoscaled engine pool and reports:

- aggregate tokens/s
- p50/p95 TTFT (client-observed time to first streamed token)
- p50/p95 per-token latency (inter-token gap over the stream)
- the replica-count trajectory (scale-up under backlog AND scale-down
  after drain)

Sessions ride the engine's decoupled submit/collect API: one batched
``collect`` RPC per replica per tick serves every session parked there,
so client RPC rate scales with the poll rate, not the session count —
the pattern that makes 1k+ concurrent sessions drivable from one
process on the CPU test platform.

A/B: ``--mode baseline`` runs the SAME harness against a
one-request-per-call replica (the pre-engine serving shape: every
request is its own ``generate()``); ``--mode engine`` is the
continuous-batching pool. ``--mode all`` (default) runs both plus the
same-process KV-handoff probe (device-object copy counters) and the
handle-routing A/B microbench (pushed stats vs per-request stats RPCs).

On TPU hosts pin replicas to chips via ``--num-tpus-per-replica``; the
default preset is CPU-sized.
"""

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ENGINE_POOL = "llm-engine"
BASELINE_POOL = "llm-baseline"


def _engine_config(args):
    # CPU-preset model sized so DECODE IS WEIGHT-STREAMING BOUND (the
    # production LLM regime): per batch-1 token the head alone streams
    # vocab*d_model*4B = 65 MB, so one-request-per-call throughput caps
    # at memory bandwidth / 65 MB while the slotted batch amortizes the
    # stream across every occupied slot — the continuous-batching win
    # the A/B measures.
    return dict(
        preset="llama-tiny",
        model_overrides={"n_layers": args.model_layers,
                         "d_model": args.model_dim,
                         "n_heads": 8,
                         "d_ff": args.model_dim * 3,
                         "dtype": "float32"},
        max_slots=args.max_slots,
        max_len=64,
        prompt_buckets=(16,),
        max_new_tokens=32,
        max_queue=8192,
    )


def _autoscaling(args):
    from ray_tpu.serve.config import AutoscalingConfig

    return AutoscalingConfig(
        min_replicas=1, max_replicas=args.max_replicas,
        target_ongoing_requests=args.target_ongoing,
        upscale_delay_s=0.3, downscale_delay_s=1.5,
        look_back_period_s=1.5)


class _Session:
    __slots__ = ("sid", "rng", "req_id", "t_submit", "t_first", "t_prev",
                 "gaps", "tokens", "replica")

    def __init__(self, sid):
        self.sid = sid
        self.rng = random.Random(sid)
        self.req_id = None
        self.replica = None
        self.t_submit = 0.0
        self.t_first = None
        self.t_prev = None
        self.gaps = []
        self.tokens = 0

    def make_request(self, n_tokens):
        plen = self.rng.randint(4, 12)
        return {"prompt": [self.rng.randint(1, 30000) for _ in
                           range(plen)],
                "n": n_tokens, "seed": self.sid}


def _percentiles(xs, ps=(50, 95)):
    if not xs:
        return {f"p{p}": None for p in ps}
    xs = sorted(xs)
    return {f"p{p}": round(xs[min(len(xs) - 1,
                                  int(len(xs) * p / 100))], 4)
            for p in ps}


def _pool_replicas(pool):
    import ray_tpu
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    return ray_tpu.get(ctrl.get_replicas.remote(pool), timeout=10)


def _replica_count(pool):
    from ray_tpu import serve

    # serve.status() returns {} while the controller (re)starts — never
    # assume the key exists (the old bench KeyError'd here).
    return serve.status().get(pool, {}).get("num_replicas", 0)


def run_engine_load(args):
    """Closed-loop sessions against the continuous-batching pool via
    submit + per-replica batched collect."""
    import ray_tpu

    sessions = [_Session(i) for i in range(args.sessions)]
    ttfts, per_token, latencies = [], [], []
    done_requests = 0
    total_tokens = 0
    trajectory = []

    replicas = _pool_replicas(ENGINE_POOL)
    if not replicas:
        raise RuntimeError("engine pool has no replicas")
    rr = 0

    def start_session(s, now):
        nonlocal rr
        s.replica = replicas[rr % len(replicas)]
        rr += 1
        s.t_submit = now
        s.t_first = None
        s.t_prev = None
        s.gaps = []
        s.tokens = 0
        s.req_id = None
        # Replicas are generic serve wrappers: engine methods dispatch
        # through handle_request(method, args, kwargs).
        return s.replica.handle_request.remote(
            "submit", (s.make_request(args.new_tokens),), {})

    trajectory.append(_replica_count(ENGINE_POOL))  # pre-flood floor
    now = time.perf_counter()
    pending_submit = {start_session(s, now): s for s in sessions}
    t_end = time.perf_counter() + args.duration
    t_sample = 0.0
    issuing = True

    while True:
        now = time.perf_counter()
        if now >= t_sample:
            trajectory.append(_replica_count(ENGINE_POOL))
            replicas = _pool_replicas(ENGINE_POOL) or replicas
            t_sample = now + 0.5
        if issuing and now >= t_end:
            issuing = False

        # Resolve submit acks -> request ids.
        if pending_submit:
            refs = list(pending_submit)
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                    timeout=0.02)
            for ref in ready:
                s = pending_submit.pop(ref)
                try:
                    s.req_id = ray_tpu.get(ref, timeout=5)
                except Exception:
                    if issuing:   # replica died (downscale): resubmit
                        pending_submit[start_session(s, now)] = s

        # One batched collect per replica serves all its sessions.
        by_replica = {}
        for s in sessions:
            if s.req_id is not None:
                by_replica.setdefault(id(s.replica), []).append(s)
        for group in by_replica.values():
            rep = group[0].replica
            ids = [s.req_id for s in group]
            try:
                res = ray_tpu.get(
                    rep.handle_request.remote("collect", (ids,), {}),
                    timeout=10)
            except Exception:
                for s in group:   # replica died: restart the session
                    s.req_id = None
                    if issuing:
                        pending_submit[start_session(s, now)] = s
                continue
            now = time.perf_counter()
            for s in group:
                out = res.get(s.req_id) or {}
                got = out.get("tokens") or []
                if got:
                    if s.t_first is None:
                        s.t_first = now
                        ttfts.append(now - s.t_submit)
                    else:
                        gap = (now - s.t_prev) / len(got)
                        s.gaps.extend([gap] * len(got))
                    s.t_prev = now
                    s.tokens += len(got)
                if out.get("done"):
                    done_requests += 1
                    total_tokens += s.tokens
                    latencies.append(now - s.t_submit)
                    per_token.extend(s.gaps)
                    s.req_id = None
                    if issuing:
                        pending_submit[start_session(s, now)] = s

        outstanding = pending_submit or any(
            s.req_id is not None for s in sessions)
        if not issuing and not outstanding:
            break
        time.sleep(args.tick)

    wall = time.perf_counter() - (t_end - args.duration)
    # Post-drain: watch the pool scale back down.
    floor_deadline = time.time() + args.downscale_wait
    while time.time() < floor_deadline:
        n = _replica_count(ENGINE_POOL)
        trajectory.append(n)
        if n <= 1:
            break
        time.sleep(0.5)

    return {
        "metric": "llm_serve_engine",
        "mode": "continuous_batching",
        "sessions": args.sessions,
        "requests": done_requests,
        "tokens_per_sec": round(total_tokens / wall, 1),
        "ttft_s": _percentiles(ttfts),
        "per_token_s": _percentiles(per_token),
        "request_latency_s": _percentiles(latencies),
        "replica_trajectory": trajectory,
        "max_replicas_seen": max(trajectory or [0]),
        "scaled_up": max(trajectory or [0]) > 1,
        "scaled_down": bool(trajectory) and trajectory[-1] <= 1,
    }


def run_baseline_load(args):
    """The same closed-loop session harness against one-request-per-call
    replicas (each request is a full blocking ``generate()``)."""
    import ray_tpu
    from ray_tpu import serve

    handle = serve.get_deployment_handle(BASELINE_POOL)
    sessions = [_Session(i) for i in range(args.sessions)]
    latencies = []
    done_requests = 0
    total_tokens = 0
    trajectory = []

    def start(s, now):
        s.t_submit = now
        req = s.make_request(args.new_tokens)
        req["prompt"] += [0] * (16 - len(req["prompt"]))  # one jit shape
        return handle.remote(req).ref

    now = time.perf_counter()
    outstanding = {start(s, now): s for s in sessions}
    t_end = time.perf_counter() + args.duration
    t_sample = 0.0
    issuing = True

    while outstanding:
        now = time.perf_counter()
        if now >= t_sample:
            trajectory.append(_replica_count(BASELINE_POOL))
            t_sample = now + 0.5
        if issuing and now >= t_end:
            issuing = False
        refs = list(outstanding)
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                timeout=0.2)
        now = time.perf_counter()
        for ref in ready:
            s = outstanding.pop(ref)
            try:
                out = ray_tpu.get(ref, timeout=5)
                n_toks = len(out["tokens"])
            except Exception:
                n_toks = 0   # replica died; count nothing
            if n_toks:
                done_requests += 1
                total_tokens += n_toks
                latencies.append(now - s.t_submit)
            if issuing:
                outstanding[start(s, now)] = s

    wall = time.perf_counter() - (t_end - args.duration)
    floor_deadline = time.time() + args.downscale_wait
    while time.time() < floor_deadline:
        n = _replica_count(BASELINE_POOL)
        trajectory.append(n)
        if n <= 1:
            break
        time.sleep(0.5)

    return {
        "metric": "llm_serve_baseline",
        "mode": "one_request_per_call",
        "sessions": args.sessions,
        "requests": done_requests,
        "tokens_per_sec": round(total_tokens / wall, 1),
        # No streaming in the baseline: the first token arrives with the
        # whole response, so TTFT == request latency.
        "ttft_s": _percentiles(latencies),
        "per_token_s": _percentiles(
            [latency / args.new_tokens for latency in latencies]),
        "request_latency_s": _percentiles(latencies),
        "replica_trajectory": trajectory,
        "max_replicas_seen": max(trajectory or [0]),
    }


def run_handoff_probe(args):
    """Same-process prefill -> publish -> adopt -> decode with the
    device-object copy counters: the KV handoff must show ZERO host
    materializations (and by-reference local hits) on this platform."""
    import jax
    import jax.numpy as jnp

    from ray_tpu._private import device_objects
    from ray_tpu.models.generate import (
        adopt_slot, decode_step, init_slotted_cache, prefill_slot,
    )
    from ray_tpu.serve.llm import EngineConfig, adopt_kv, publish_kv
    from ray_tpu.serve.llm.replicas import _build_model

    ec = EngineConfig.from_dict(_engine_config(args))
    cfg, params = _build_model(ec)
    prompt = [5, 9, 2, 11, 3]
    padded = jnp.zeros((1, 16), jnp.int32).at[:, :len(prompt)].set(
        jnp.asarray(prompt, jnp.int32))
    first, kv = prefill_slot(params, padded, jnp.int32(len(prompt)),
                             jnp.int32(0), cfg=cfg)
    jax.block_until_ready(kv)
    device_objects.reset_stats()
    t0 = time.perf_counter()
    handoff = publish_kv(kv, len(prompt), int(first[0]), n=8, seed=0)
    adopted = adopt_kv(handoff)
    handoff_ms = (time.perf_counter() - t0) * 1e3
    stats = device_objects.stats()

    # And prove the adopted cache decodes: 8 greedy tokens.
    cache = adopt_slot(init_slotted_cache(cfg, 2, ec.max_len),
                       jnp.int32(0), adopted, jnp.int32(len(prompt)))
    last = jnp.zeros((2,), jnp.int32).at[0].set(handoff["first_token"])
    active = jnp.zeros((2,), bool).at[0].set(True)
    toks = [handoff["first_token"]]
    for _ in range(7):
        nxt, cache = decode_step(params, cache, last, active,
                                 jnp.zeros((2,), jnp.int32), cfg=cfg)
        toks.append(int(nxt[0]))
        last = last.at[0].set(nxt[0])
    return {
        "metric": "llm_kv_handoff_probe",
        "host_materializations": stats["host_materializations"],
        "local_hits": stats["local_hits"],
        "rebuilds": stats["rebuilds"],
        "staged_bytes": stats["staged_bytes"],
        "handoff_ms": round(handoff_ms, 3),
        "decoded_tokens": len(toks),
        "zero_copy": stats["host_materializations"] == 0,
    }


def run_handle_ab(args):
    """Handle routing A/B: pushed per-replica loads (zero hot-path RPCs)
    vs the legacy two-stats-RPCs-per-request probe."""
    import threading

    from ray_tpu import serve
    from ray_tpu._private.config import config

    @serve.deployment(num_replicas=2, name="route-ab")
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), http_port=None)
    handle.remote(0).result(timeout=30)

    def rps(duration=3.0, threads=4):
        stop = time.perf_counter() + duration
        counts = [0] * threads

        def worker(i):
            while time.perf_counter() < stop:
                handle.remote(i).result(timeout=30)
                counts[i] += 1

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return sum(counts) / duration

    config.set("serve_handle_stats_rpc", True)
    rps_rpc = rps()
    config.set("serve_handle_stats_rpc", False)
    rps_pushed = rps()
    serve.delete("route-ab")
    return {
        "metric": "serve_handle_routing_ab",
        "rps_stats_rpc": round(rps_rpc, 1),
        "rps_pushed_stats": round(rps_pushed, 1),
        "speedup": round(rps_pushed / max(rps_rpc, 1e-9), 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", default="all",
                    choices=["all", "engine", "baseline", "probe",
                             "handle-ab"])
    ap.add_argument("--sessions", type=int, default=1000)
    ap.add_argument("--duration", type=float, default=15.0,
                    help="load-phase seconds per mode")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--max-slots", type=int, default=32)
    ap.add_argument("--model-dim", type=int, default=512)
    ap.add_argument("--model-layers", type=int, default=4)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--target-ongoing", type=float, default=32.0,
                    help="autoscaler target load per engine replica")
    ap.add_argument("--tick", type=float, default=0.025,
                    help="collect poll period (s)")
    ap.add_argument("--downscale-wait", type=float, default=45.0)
    ap.add_argument("--baseline-static-replicas", type=int, default=3,
                    help="pre-grant the one-call baseline this many "
                         "static replicas (0 = autoscaled like the "
                         "engine pool)")
    ap.add_argument("--num-tpus-per-replica", type=int, default=0)
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.llm import build_llm_app

    ray_tpu.init(num_cpus=8, object_store_memory=512 * 1024 * 1024)
    serve.start(http_port=None)
    results = []
    opts = {"num_tpus": args.num_tpus_per_replica} \
        if args.num_tpus_per_replica else None
    try:
        if args.mode in ("all", "probe"):
            results.append(run_handoff_probe(args))
            print(json.dumps(results[-1]), flush=True)

        if args.mode in ("all", "engine"):
            handle = serve.run(
                build_llm_app(_engine_config(args), mode="combined",
                              name="llm",
                              autoscaling_config=_autoscaling(args),
                              ray_actor_options=opts),
                route_prefix="/llm")
            handle.remote({"prompt": [1, 2, 3],
                           "n": args.new_tokens}).result(timeout=600)
            results.append(run_engine_load(args))
            print(json.dumps(results[-1]), flush=True)
            serve.delete("llm")
            serve.delete(ENGINE_POOL)

        if args.mode in ("all", "baseline"):
            from ray_tpu.serve.llm.replicas import normalize_request

            ecfg = _engine_config(args)

            # The blocking one-call-per-request shape starves the
            # controller's stats probes under load (every actor thread
            # is parked in generate()), so its autoscaler rarely fires —
            # itself a finding. --baseline-static-replicas N grants the
            # baseline the engine pool's PEAK capacity up front instead,
            # the strongest version of the comparison.
            static_n = args.baseline_static_replicas
            @serve.deployment(
                name=BASELINE_POOL, max_ongoing_requests=64,
                num_replicas=static_n or 1,
                autoscaling_config=None if static_n
                else _autoscaling(args),
                ray_actor_options=opts or {})
            class OneCallLLM:
                """Pre-engine shape: every request runs its own
                ``generate()`` — no batching across requests."""

                def __init__(self):
                    import jax as _jax

                    from ray_tpu.serve.llm import EngineConfig
                    from ray_tpu.serve.llm.replicas import _build_model

                    self._jax = _jax
                    ec = EngineConfig.from_dict(ecfg)
                    self.cfg, self.params = _build_model(ec)

                def __call__(self, request):
                    import jax.numpy as _jnp

                    from ray_tpu.models.generate import generate

                    req = normalize_request(request)
                    out = generate(
                        self.params,
                        _jnp.asarray([req["prompt"]], _jnp.int32),
                        self._jax.random.key(req["seed"]),
                        cfg=self.cfg, max_new_tokens=req["n"] or 16,
                        temperature=0.0)
                    return {"tokens": [int(t) for t in out[0]]}

            handle = serve.run(OneCallLLM.bind(), http_port=None)
            handle.remote({"prompt": [1, 2, 3] + [0] * 13,
                           "n": args.new_tokens}).result(timeout=600)
            results.append(run_baseline_load(args))
            print(json.dumps(results[-1]), flush=True)
            serve.delete(BASELINE_POOL)

        if args.mode in ("all", "handle-ab"):
            results.append(run_handle_ab(args))
            print(json.dumps(results[-1]), flush=True)

        eng = next((r for r in results
                    if r["metric"] == "llm_serve_engine"), None)
        base = next((r for r in results
                     if r["metric"] == "llm_serve_baseline"), None)
        if eng and base:
            print(json.dumps({
                "metric": "llm_serve_ab_summary",
                "engine_tokens_per_sec": eng["tokens_per_sec"],
                "baseline_tokens_per_sec": base["tokens_per_sec"],
                "speedup": round(eng["tokens_per_sec"] /
                                 max(base["tokens_per_sec"], 1e-9), 2),
            }), flush=True)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
