"""BASELINE config 5: LLM inference deployment with autoscaled
replicas — a llama-style decoder served through ray_tpu.serve, driven
with concurrent requests until queue-depth autoscaling adds replicas.

On TPU hosts each replica pins chips via ray_actor_options
{"num_tpus": N}; this harness runs the "llama-tiny" preset so it also
executes on the CPU test platform.

Prints JSON lines: per-phase tokens/s and the replica count trajectory.
"""

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--requests-per-client", type=int, default=4)
    args = ap.parse_args()

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.serve.config import AutoscalingConfig

    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    serve.start()
    try:
        new_tokens = args.new_tokens

        @serve.deployment(
            name="llm",
            autoscaling_config=AutoscalingConfig(
                min_replicas=1, max_replicas=3,
                target_ongoing_requests=1.0,
                upscale_delay_s=0.2, look_back_period_s=1.0),
        )
        class LLM:
            def __init__(self):
                import jax
                import numpy as np

                from ray_tpu.models import GPTConfig, init_params
                from ray_tpu.models.generate import generate

                self.cfg = GPTConfig.preset("llama-tiny", n_layers=2,
                                            max_seq=128)
                self.params = init_params(jax.random.key(0), self.cfg)
                self._generate = generate
                self._jax = jax
                self._np = np

            def __call__(self, req):
                import jax.numpy as jnp

                prompt = jnp.asarray(
                    self._np.asarray(req["prompt"], self._np.int32))[None]
                out = self._generate(
                    self.params, prompt, self._jax.random.key(0),
                    cfg=self.cfg, max_new_tokens=req["n"])
                return {"tokens": self._np.asarray(out)[0].tolist()}

        handle = serve.run(LLM.bind(), route_prefix="/llm")
        # Warm one request (compiles the decode loop).
        out = handle.remote({"prompt": [1, 2, 3], "n": new_tokens}).result(
            timeout=600)
        assert len(out["tokens"]) >= new_tokens

        results = []
        lock = threading.Lock()

        def client(cid):
            for i in range(args.requests_per_client):
                t0 = time.perf_counter()
                handle.remote({"prompt": [1 + cid, 2, 3],
                               "n": new_tokens}).result(timeout=600)
                with lock:
                    results.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(args.clients)]
        for t in threads:
            t.start()
        replica_trajectory = []
        while any(t.is_alive() for t in threads):
            replica_trajectory.append(
                serve.status()["llm"]["num_replicas"])
            time.sleep(0.5)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        n_req = args.clients * args.requests_per_client
        print(json.dumps({
            "metric": "llm_serve_tokens_per_sec",
            "value": round(n_req * new_tokens / wall, 1),
            "unit": "tokens/s",
            "requests": n_req,
            "p50_latency_s": round(sorted(results)[len(results) // 2], 3),
            "max_replicas_seen": max(replica_trajectory or [1]),
            "replica_trajectory": replica_trajectory,
        }), flush=True)
    finally:
        serve.shutdown()
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
