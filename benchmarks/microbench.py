"""Control-plane microbenchmarks (reference:
``python/ray/_private/ray_perf.py:93-244`` — the release microbenchmark
suite: put/get calls/s, task throughput, actor call rates).

Prints one JSON line per metric. Run: python benchmarks/microbench.py
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(name, fn, n, unit="ops/s", reps=3):
    # Warm the path first (conns, caches, allocator, lease ramp): cold
    # process throughput climbs ~30% over the first seconds of life, and
    # timing from op 0 measures that ramp, not the steady state the
    # actor benchmarks (which warm up explicitly) report. Then take the
    # best of ``reps`` in-process trials: sub-second windows are
    # preempted by background threads (GC, reporters, conn serving)
    # bimodally, and a single trial reads as a phantom mode delta.
    fn(max(1, min(500, n // 10)))
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        fn(n)
        dt = time.perf_counter() - t0
        best = max(best, n / dt)
    print(json.dumps({"metric": name, "value": round(best, 1),
                      "unit": unit, "n": n, "reps": reps}), flush=True)


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=1024 * 1024 * 1024)
    try:
        # ---- plasma put/get, small objects
        def put_small(n):
            for i in range(n):
                ray_tpu.put(i)

        timed("put_calls_per_s_small", put_small, 5000)

        refs = [ray_tpu.put(i) for i in range(5000)]

        def get_small(n):
            for r in refs[:n]:
                ray_tpu.get(r)

        timed("get_calls_per_s_small", get_small, 5000)

        # ---- put GB/s, large objects
        blob = np.ones(64 << 20, np.uint8)  # 64 MiB

        def put_large(n):
            for _ in range(n):
                ray_tpu.put(blob)

        # Keep total put volume under the spill threshold (0.8 x store)
        # so this measures serialization+arena copy, not disk spill.
        # One warmup put first: the initial large create faults in fresh
        # arena pages, which is cold-start cost, not copy bandwidth.
        put_large(1)
        t0 = time.perf_counter()
        put_large(6)
        dt = time.perf_counter() - t0
        print(json.dumps({"metric": "single_client_put_gb_s",
                          "value": round(6 * 64 / 1024 / dt, 3),
                          "unit": "GB/s"}), flush=True)

        # ---- device arrays (jax.Array) through the store
        # put = arena-staged (on: OOB view straight into the slab; off:
        # legacy pickle-via-host with the tensor in-band). get = arena
        # rebuild via device_put (the same-process registry is cleared
        # each iteration so this measures the cross-process path), plus
        # the same-process by-reference hit ratio and O(1) local get.
        try:
            import jax

        except Exception:
            jax = None
        if jax is not None:
            from ray_tpu._private import device_objects
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker()
            darr = jax.device_put(blob)  # 64 MiB on device
            jax.block_until_ready(darr)
            gib = darr.nbytes / (1 << 30)

            def put_device(n):
                """One timed rep; the staged copies are deleted from the
                arena between reps (store.delete, refcount 0) so the loop
                measures staging bandwidth, not eviction/spill churn —
                the off-path's in-band pickle doubles per-put footprint
                and outruns async refcount freeing otherwise."""
                refs_ = []
                t0 = time.perf_counter()
                for _ in range(n):
                    refs_.append(ray_tpu.put(darr))
                dt = time.perf_counter() - t0
                for r in refs_:
                    w.store.delete(r.binary())
                return dt

            put_device(1)  # fault in arena pages (cold-start, not copy bw)
            best = 0.0
            for _ in range(3):
                best = max(best, 4 * gib / put_device(4))
            print(json.dumps({"metric": "device_put_gb_s",
                              "value": round(best, 3),
                              "unit": "GB/s"}), flush=True)

            dref = ray_tpu.put(darr)
            best = 0.0
            for _ in range(3):
                w._device_local.clear()   # force the arena rebuild path
                t0 = time.perf_counter()
                v = ray_tpu.get(dref)
                jax.block_until_ready(v)
                dt = time.perf_counter() - t0
                del v
                best = max(best, gib / dt)
            print(json.dumps({"metric": "device_get_gb_s",
                              "value": round(best, 3),
                              "unit": "GB/s"}), flush=True)

            device_objects.reset_stats()
            dref2 = ray_tpu.put(darr)
            t0 = time.perf_counter()
            for _ in range(200):
                ray_tpu.get(dref2)
            local_ms = (time.perf_counter() - t0) * 1000 / 200
            s = device_objects.stats()
            denom = s["local_hits"] + s["rebuilds"]
            print(json.dumps({"metric": "device_get_local_hit_ratio",
                              "value": round(
                                  s["local_hits"] / denom, 3) if denom
                              else 0.0,
                              "unit": "ratio",
                              "local_hits": s["local_hits"],
                              "rebuilds": s["rebuilds"]}), flush=True)
            print(json.dumps({"metric": "device_get_local_ms",
                              "value": round(local_ms, 4),
                              "unit": "ms"}), flush=True)
            del darr, dref, dref2

        # ---- tasks: sync round-trips and async pipelined
        @ray_tpu.remote
        def nop():
            return None

        # First-task cost (worker spawn + first lease grant) is its own
        # metric; the throughput loops below measure the steady state,
        # matching the actor benchmarks (which warm up before timing).
        t0 = time.perf_counter()
        ray_tpu.get(nop.remote())
        print(json.dumps({"metric": "task_cold_start_ms",
                          "value": round(
                              (time.perf_counter() - t0) * 1000, 1),
                          "unit": "ms"}), flush=True)

        def tasks_sync(n):
            for _ in range(n):
                ray_tpu.get(nop.remote())

        timed("tasks_sync_per_s", tasks_sync, 600)

        def tasks_async(n):
            ray_tpu.get([nop.remote() for _ in range(n)])

        timed("tasks_async_per_s", tasks_async, 2000)

        # ---- actor calls: 1:1 sync and pipelined
        @ray_tpu.remote
        class A:
            def nop(self):
                return None

        a = A.remote()
        ray_tpu.get(a.nop.remote())

        def actor_sync(n):
            for _ in range(n):
                ray_tpu.get(a.nop.remote())

        timed("actor_calls_sync_per_s", actor_sync, 500)

        def actor_async(n):
            ray_tpu.get([a.nop.remote() for _ in range(n)])

        timed("actor_calls_async_per_s", actor_async, 3000)

        # ---- n:n actor throughput
        actors = [A.remote() for _ in range(4)]
        ray_tpu.get([x.nop.remote() for x in actors])

        def actor_nn(n):
            per = n // len(actors)
            ray_tpu.get([x.nop.remote() for x in actors
                         for _ in range(per)])

        timed("actor_calls_nn_per_s", actor_nn, 4000)

        # ---- local-first scheduler: grant/spillback split for this run
        try:
            from ray_tpu._private import protocol
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker()
            addr = w._own_nm_address()
            stats = w.nm_conn(addr).request(
                protocol.SCHEDULER_STATS, {}, timeout=10)
            grants = stats["local_grants_total"]
            spills = stats["local_spillbacks_total"]
            if grants + spills:
                print(json.dumps({
                    "metric": "scheduler_local_grant_ratio",
                    "value": round(grants / (grants + spills), 3),
                    "unit": "ratio",
                    "local_grants_total": grants,
                    "local_spillbacks_total": spills,
                }), flush=True)
        except Exception:
            pass   # local scheduling off / NM unreachable: no ratio line
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
