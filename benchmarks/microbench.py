"""Control-plane microbenchmarks (reference:
``python/ray/_private/ray_perf.py:93-244`` — the release microbenchmark
suite: put/get calls/s, task throughput, actor call rates).

Prints one JSON line per metric. Run: python benchmarks/microbench.py
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(name, fn, n, unit="ops/s"):
    t0 = time.perf_counter()
    fn(n)
    dt = time.perf_counter() - t0
    print(json.dumps({"metric": name, "value": round(n / dt, 1),
                      "unit": unit, "n": n,
                      "total_s": round(dt, 3)}), flush=True)


def main():
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=1024 * 1024 * 1024)
    try:
        # ---- plasma put/get, small objects
        def put_small(n):
            for i in range(n):
                ray_tpu.put(i)

        timed("put_calls_per_s_small", put_small, 2000)

        refs = [ray_tpu.put(i) for i in range(2000)]

        def get_small(n):
            for r in refs[:n]:
                ray_tpu.get(r)

        timed("get_calls_per_s_small", get_small, 2000)

        # ---- put GB/s, large objects
        blob = np.ones(64 << 20, np.uint8)  # 64 MiB

        def put_large(n):
            for _ in range(n):
                ray_tpu.put(blob)

        # Keep total put volume under the spill threshold (0.8 x store)
        # so this measures serialization+arena copy, not disk spill.
        t0 = time.perf_counter()
        put_large(6)
        dt = time.perf_counter() - t0
        print(json.dumps({"metric": "single_client_put_gb_s",
                          "value": round(6 * 64 / 1024 / dt, 3),
                          "unit": "GB/s"}), flush=True)

        # ---- tasks: sync round-trips and async pipelined
        @ray_tpu.remote
        def nop():
            return None

        def tasks_sync(n):
            for _ in range(n):
                ray_tpu.get(nop.remote())

        timed("tasks_sync_per_s", tasks_sync, 300)

        def tasks_async(n):
            ray_tpu.get([nop.remote() for _ in range(n)])

        timed("tasks_async_per_s", tasks_async, 2000)

        # ---- actor calls: 1:1 sync and pipelined
        @ray_tpu.remote
        class A:
            def nop(self):
                return None

        a = A.remote()
        ray_tpu.get(a.nop.remote())

        def actor_sync(n):
            for _ in range(n):
                ray_tpu.get(a.nop.remote())

        timed("actor_calls_sync_per_s", actor_sync, 500)

        def actor_async(n):
            ray_tpu.get([a.nop.remote() for _ in range(n)])

        timed("actor_calls_async_per_s", actor_async, 3000)

        # ---- n:n actor throughput
        actors = [A.remote() for _ in range(4)]
        ray_tpu.get([x.nop.remote() for x in actors])

        def actor_nn(n):
            per = n // len(actors)
            ray_tpu.get([x.nop.remote() for x in actors
                         for _ in range(per)])

        timed("actor_calls_nn_per_s", actor_nn, 4000)
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
