"""GPT-2 125M single-chip training sweep: batch x remat policy x
attention backend. Prints one JSON line per config (chained-dispatch
timing, one sync per measurement window — robust to tunnel RTT).

Usage: python benchmarks/gpt2_sweep.py [--quick]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seq", type=int, default=1024)
    args = ap.parse_args()

    import jax

    cache = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass
    import jax.numpy as jnp
    import numpy as np
    import optax

    from ray_tpu.models import (
        GPTConfig, count_params, make_train_state, make_train_step,
    )

    def peak():
        kind = (jax.devices()[0].device_kind or "").lower()
        for k, v in {"v5e": 197e12, "v4": 275e12, "v5p": 459e12,
                     "v6e": 918e12}.items():
            if k in kind:
                return v
        return 197e12

    def run(batch, chain=8, **ov):
        try:
            cfg = GPTConfig.preset("gpt2-125m", max_seq=args.seq, **ov)
            opt = optax.adamw(3e-4, weight_decay=0.1)
            state = make_train_state(jax.random.key(0), cfg, opt)
            step = jax.jit(make_train_step(cfg, opt), donate_argnums=0)
            rng = np.random.default_rng(0)
            toks = jnp.asarray(rng.integers(
                0, cfg.vocab_size, (batch, args.seq + 1)), jnp.int32)
            data = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
            t0 = time.perf_counter()
            step = step.lower(state, data).compile()
            compile_s = round(time.perf_counter() - t0, 1)
            for _ in range(2):
                state, m = step(state, data)
            float(jax.device_get(m["loss"]))
            t0 = time.perf_counter()
            for _ in range(chain):
                state, m = step(state, data)
            float(jax.device_get(m["loss"]))
            dt = (time.perf_counter() - t0) / chain
            n = count_params(state.params)
            tps = batch * args.seq / dt
            print(json.dumps({
                "batch": batch, "overrides": {k: str(v)
                                              for k, v in ov.items()},
                "step_ms": round(dt * 1e3, 1),
                "tokens_per_sec": round(tps, 0),
                "mfu": round(tps * 6 * n / peak(), 4),
                "compile_s": compile_s,
            }), flush=True)
        except Exception as e:
            print(json.dumps({
                "batch": batch, "overrides": {k: str(v)
                                              for k, v in ov.items()},
                "error": f"{type(e).__name__}: {str(e)[:200]}",
            }), flush=True)

    # XLA fused attention (the seq-1024 winner) across batch + remat.
    run(32, flash_attention=False)
    run(32, flash_attention=False, remat_policy="matmuls")
    if not args.quick:
        run(48, flash_attention=False)
        run(48, flash_attention=False, remat_policy="matmuls")
        run(64, flash_attention=False, remat_policy="matmuls")
        # Pallas flash for reference at this length.
        run(32, flash_attention=True)


if __name__ == "__main__":
    main()
