"""Scheduler ready-queue indexing: scheduling cost per event is
O(shapes + dispatched), not O(queue length) (reference:
raylet/scheduling/cluster_task_manager.h:42 scheduling classes)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def ray_2cpu():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_blocked_queue_does_not_tax_scheduling(ray_2cpu):
    """With thousands of infeasible tasks queued (one shape), feasible
    work schedules with O(1) bucket checks per event — measured by
    counting placement attempts, not wall clock."""
    from ray_tpu._private import worker as worker_mod

    gcs = worker_mod._global_cluster.gcs

    @ray_tpu.remote
    def wants_gpu():
        return "never"

    @ray_tpu.remote
    def cpu_work(i):
        return i

    n_blocked = 3000
    blocked = [wants_gpu.options(num_gpus=1).remote()
               for _ in range(n_blocked)]
    # Let the queue build up.
    deadline = time.time() + 30
    while len(gcs._queued_tasks) < n_blocked and time.time() < deadline:
        time.sleep(0.05)
    assert len(gcs._queued_tasks) >= n_blocked

    # Count placement attempts while 50 feasible tasks run to completion.
    counter = {"n": 0}
    orig = gcs._pick_node

    def counting_pick(*a, **k):
        counter["n"] += 1
        return orig(*a, **k)

    gcs._pick_node = counting_pick
    try:
        out = ray_tpu.get([cpu_work.remote(i) for i in range(50)],
                          timeout=120)
    finally:
        gcs._pick_node = orig
    assert out == list(range(50))
    # An O(queue) rescan would re-examine the 3000 blocked specs on every
    # event (>100k attempts); the indexed queue checks one bucket head.
    assert counter["n"] < 3000, (
        f"{counter['n']} placement attempts for 50 tasks with a blocked "
        f"queue of {n_blocked} — scheduler is O(queue)")
    del blocked
