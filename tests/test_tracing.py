"""Trace-context propagation (reference: util/tracing/tracing_helper.py
:284,318 — _ray_trace_ctx injected across process hops; here the context
rides task specs and spans ride the task-event machinery)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _events_by_name(names, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        evs = {e["name"]: e for e in ray_tpu.timeline()
               if e.get("name") in names}
        if set(names) <= set(evs):
            return evs
        time.sleep(0.2)
    raise AssertionError(f"events {names} not all reported: {evs}")


def test_trace_spans_driver_task_nested(ray_cluster):
    """driver -> task -> nested task: one trace id, parent links follow
    the submission chain."""
    @ray_tpu.remote
    def inner():
        return "leaf"

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner.remote())

    assert ray_tpu.get(outer.remote(), timeout=60) == "leaf"
    evs = _events_by_name(["outer", "inner"])
    o, i = evs["outer"], evs["inner"]
    assert o["trace_id"] and o["span_id"]
    assert i["trace_id"] == o["trace_id"]       # same trace
    assert i["parent_span_id"] == o["span_id"]  # nested under outer
    assert o["parent_span_id"] is None          # driver-side root


def test_trace_spans_actor_hop(ray_cluster):
    """driver -> actor method -> task submitted from the actor."""
    @ray_tpu.remote
    def from_actor():
        return 1

    @ray_tpu.remote
    class A:
        def call(self):
            return ray_tpu.get(from_actor.remote())

    a = A.remote()
    assert ray_tpu.get(a.call.remote(), timeout=60) == 1
    evs = _events_by_name(["call", "from_actor"])
    c, f = evs["call"], evs["from_actor"]
    assert c["trace_id"]
    assert f["trace_id"] == c["trace_id"]
    assert f["parent_span_id"] == c["span_id"]


def test_separate_roots_get_separate_traces(ray_cluster):
    @ray_tpu.remote
    def t_a():
        return None

    @ray_tpu.remote
    def t_b():
        return None

    ray_tpu.get([t_a.remote(), t_b.remote()], timeout=60)
    evs = _events_by_name(["t_a", "t_b"])
    assert evs["t_a"]["trace_id"] != evs["t_b"]["trace_id"]


def test_two_hop_chain_renders_connected_chrome_trace(ray_cluster):
    """ISSUE 8 satellite: the chrome-trace export carries parent/child
    relationships (flow events + span args), so a two-hop task chain
    renders as one connected trace instead of flat slices."""
    from ray_tpu.scripts.cli import build_chrome_trace

    @ray_tpu.remote
    def hop2():
        return "leaf"

    @ray_tpu.remote
    def hop1():
        return ray_tpu.get(hop2.remote())

    assert ray_tpu.get(hop1.remote(), timeout=60) == "leaf"
    evs = _events_by_name(["hop1", "hop2"])
    trace = build_chrome_trace(list(evs.values()))

    slices = {t["name"]: t for t in trace if t["ph"] == "X"}
    assert slices["hop1"]["args"]["span_id"] == evs["hop1"]["span_id"]
    assert slices["hop2"]["args"]["parent_span_id"] == \
        evs["hop1"]["span_id"]
    # Flow pair: starts inside hop1's slice, finishes at hop2's start,
    # bound together by the child's span id.
    starts = [t for t in trace if t["ph"] == "s"]
    finishes = [t for t in trace if t["ph"] == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"] == evs["hop2"]["span_id"]
    assert starts[0]["ts"] == pytest.approx(evs["hop1"]["start"] * 1e6)
    assert finishes[0]["ts"] == pytest.approx(evs["hop2"]["start"] * 1e6)
    assert finishes[0]["bp"] == "e"


def test_collective_ops_emit_spans_under_task(ray_cluster):
    """Collective _exchange operations join the task-event stream as
    spans parented under the rank's running task."""
    @ray_tpu.remote
    class Rank:
        def __init__(self, rank):
            self.rank = rank

        def join_and_reduce(self, world):
            import numpy as np

            from ray_tpu.parallel import collective

            collective.init_collective_group(
                world, self.rank, backend="store",
                group_name="span_g")
            return collective.allreduce(
                np.ones(2), group_name="span_g").tolist()

    r0, r1 = Rank.remote(0), Rank.remote(1)
    out = ray_tpu.get([r0.join_and_reduce.remote(2),
                       r1.join_and_reduce.remote(2)], timeout=120)
    assert out == [[2.0, 2.0], [2.0, 2.0]]

    deadline = time.time() + 20
    spans = []
    while time.time() < deadline:
        spans = [e for e in ray_tpu.timeline()
                 if e.get("kind") == "collective"
                 and "allreduce" in e["name"]]
        if len(spans) >= 2:
            break
        time.sleep(0.2)
    assert len(spans) >= 2, spans   # one per rank
    tasks = {e["span_id"]: e for e in ray_tpu.timeline()
             if e.get("name") == "join_and_reduce"}
    for s in spans:
        parent = tasks.get(s["parent_span_id"])
        assert parent is not None, s
        assert s["trace_id"] == parent["trace_id"]


def test_serve_device_object_round_trip_single_trace(ray_cluster):
    """ISSUE 8 acceptance: a serve → replica → device-object (KV
    publish/adopt) round trip produces ONE connected trace spanning the
    handle hop, the task run, and the KV-cache transfer spans."""
    from ray_tpu import serve

    @serve.deployment
    class KVEcho:
        def __call__(self, n):
            import jax.numpy as jnp

            from ray_tpu.serve.llm.kv_transfer import adopt_kv, publish_kv

            arr = jnp.ones((8, 8), jnp.float32)
            handoff = publish_kv({"k": arr, "v": arr}, 8, 5)
            kv = adopt_kv(handoff)
            return float(kv["k"].sum())

    handle = serve.run(KVEcho.bind(), name="kvecho")
    try:
        assert handle.remote(1).result(timeout=120) == 64.0

        deadline = time.time() + 20
        evs, hops = [], []
        while time.time() < deadline:
            evs = ray_tpu.timeline()
            hops = [e for e in evs if e.get("kind") == "serve_handle"
                    and "kvecho" in e["name"]]
            if hops:
                run_evs = [e for e in evs
                           if e.get("parent_span_id") ==
                           hops[0]["span_id"]]
                dev = [e for e in evs
                       if e.get("kind") in ("device_put", "device_get")]
                if run_evs and dev:
                    break
            time.sleep(0.2)
        assert hops, "no serve_handle span reported"
        hop = hops[0]
        # handle hop -> replica task run (parent link crosses the hop).
        runs = [e for e in evs
                if e.get("parent_span_id") == hop["span_id"]
                and e.get("kind") == "actor_task"]
        assert runs, evs
        run_ev = runs[0]
        # task run -> KV transfer spans (publish = device_put x2,
        # adopt = device_get x2), all inside the same trace.
        kv_spans = [e for e in evs
                    if e.get("parent_span_id") == run_ev["span_id"]
                    and e.get("kind") in ("device_put", "device_get")]
        kinds = {e["kind"] for e in kv_spans}
        assert kinds == {"device_put", "device_get"}, kv_spans
        trace_ids = {hop["trace_id"], run_ev["trace_id"]} | \
            {e["trace_id"] for e in kv_spans}
        assert len(trace_ids) == 1, trace_ids

        # And the chrome export connects all of it with flow events.
        from ray_tpu.scripts.cli import build_chrome_trace

        connected = [hop, run_ev] + kv_spans
        flows = [t for t in build_chrome_trace(connected)
                 if t["ph"] in ("s", "f")]
        # one s/f pair per child edge: run under hop + each kv span.
        assert len(flows) == 2 * (1 + len(kv_spans))
    finally:
        serve.shutdown()


def test_span_helpers_driverside(ray_cluster):
    """Driverside spans (no worker executor sink) buffer and flush over
    the GCS channel; nesting links parents."""
    from ray_tpu.util import tracing

    with tracing.span("outer_op", kind="bench") as outer_sid:
        with tracing.span("inner_op", kind="bench"):
            pass
    tracing.flush_spans()

    deadline = time.time() + 15
    evs = {}
    while time.time() < deadline:
        evs = {e["name"]: e for e in ray_tpu.timeline()
               if e.get("kind") == "bench"}
        if {"outer_op", "inner_op"} <= set(evs):
            break
        time.sleep(0.2)
    assert {"outer_op", "inner_op"} <= set(evs), evs
    assert evs["inner_op"]["parent_span_id"] == outer_sid
    assert evs["inner_op"]["trace_id"] == evs["outer_op"]["trace_id"]
    # Span events must not leak into the TASK views.
    from ray_tpu.experimental import state

    names = {t["name"] for t in state.list_tasks()}
    assert "outer_op" not in names and "inner_op" not in names
    assert "outer_op" not in state.summarize_tasks()


# ---------------------------------------------------- span sampling
# (ISSUE 12 satellite: head-based trace_sample_rate, decided once per
# request at the serve handle root and propagated with the context so a
# trace is never half-kept; errored and shed requests always kept)


_SPAN_KINDS = ("serve_handle", "serve_replica", "serve_ingress")


def _serve_spans():
    return [e for e in ray_tpu.timeline()
            if e.get("kind") in _SPAN_KINDS]


@pytest.fixture
def sampled_out():
    """trace_sample_rate=0 for the duration of the test (restored after
    — the config registry is process-global)."""
    from ray_tpu._private.config import config

    config.set("trace_sample_rate", 0.0)
    yield
    config.set("trace_sample_rate", 1.0)


def test_sampled_out_serve_round_trip_emits_zero_spans(
        ray_cluster, sampled_out):
    """With the root sampled out, NO span of the round trip is emitted —
    not the handle root (driver side) and not the replica-side span
    (the decision propagates across the process hop): never half-kept."""
    from ray_tpu import serve
    from ray_tpu.util import tracing

    @serve.deployment
    class Echo:
        def __call__(self, fail=False):
            from ray_tpu.util import tracing as t

            with t.span("replica_work", kind="serve_replica"):
                if fail:
                    raise ValueError("boom")
                return 1

    handle = serve.run(Echo.bind(), name="sampled_echo")
    try:
        assert handle.remote(False).result(timeout=120) == 1
        tracing.flush_spans()
        time.sleep(1.5)   # > the worker event-flush period
        assert _serve_spans() == [], _serve_spans()
    finally:
        serve.shutdown()


def test_errored_serve_round_trip_keeps_all_spans(
        ray_cluster, sampled_out):
    """Sampling never hides failures: an errored round trip emits ALL
    its spans (handle root with status=error via the deferred-outcome
    emission, replica-side span with status=error) even at rate 0."""
    from ray_tpu import serve

    @serve.deployment
    class Echo2:
        def __call__(self, fail=False):
            from ray_tpu.util import tracing as t

            with t.span("replica_work", kind="serve_replica"):
                if fail:
                    raise ValueError("boom")
                return 1

    handle = serve.run(Echo2.bind(), name="sampled_echo2")
    try:
        with pytest.raises(ValueError, match="boom"):
            handle.remote(True).result(timeout=120)
        from ray_tpu.util import tracing

        tracing.flush_spans()
        deadline = time.time() + 20
        spans = []
        while time.time() < deadline:
            spans = _serve_spans()
            if {"serve_handle", "serve_replica"} <= \
                    {s["kind"] for s in spans}:
                break
            time.sleep(0.2)
        kinds = {s["kind"]: s for s in spans}
        assert "serve_handle" in kinds and "serve_replica" in kinds, spans
        assert kinds["serve_handle"]["status"] == "error"
        assert kinds["serve_replica"]["status"] == "error"
        # Same trace: the decision and identity propagated as one.
        assert kinds["serve_handle"]["trace_id"] == \
            kinds["serve_replica"]["trace_id"]
    finally:
        serve.shutdown()


def test_sampled_in_serve_round_trip_keeps_spans(ray_cluster):
    """Rate 1.0 (default): the ok round trip emits its spans — the
    sampled-out test above is measuring the knob, not a regression."""
    from ray_tpu import serve

    @serve.deployment
    class Echo3:
        def __call__(self):
            return 1

    handle = serve.run(Echo3.bind(), name="sampled_echo3")
    try:
        assert handle.remote().result(timeout=120) == 1
        from ray_tpu.util import tracing

        tracing.flush_spans()
        deadline = time.time() + 20
        hops = []
        while time.time() < deadline:
            hops = [e for e in ray_tpu.timeline()
                    if e.get("kind") == "serve_handle"
                    and "sampled_echo3" in e["name"]]
            if hops:
                break
            time.sleep(0.2)
        assert hops and hops[0]["status"] == "ok", hops
    finally:
        serve.shutdown()
