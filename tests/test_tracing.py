"""Trace-context propagation (reference: util/tracing/tracing_helper.py
:284,318 — _ray_trace_ctx injected across process hops; here the context
rides task specs and spans ride the task-event machinery)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _events_by_name(names, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        evs = {e["name"]: e for e in ray_tpu.timeline()
               if e.get("name") in names}
        if set(names) <= set(evs):
            return evs
        time.sleep(0.2)
    raise AssertionError(f"events {names} not all reported: {evs}")


def test_trace_spans_driver_task_nested(ray_cluster):
    """driver -> task -> nested task: one trace id, parent links follow
    the submission chain."""
    @ray_tpu.remote
    def inner():
        return "leaf"

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner.remote())

    assert ray_tpu.get(outer.remote(), timeout=60) == "leaf"
    evs = _events_by_name(["outer", "inner"])
    o, i = evs["outer"], evs["inner"]
    assert o["trace_id"] and o["span_id"]
    assert i["trace_id"] == o["trace_id"]       # same trace
    assert i["parent_span_id"] == o["span_id"]  # nested under outer
    assert o["parent_span_id"] is None          # driver-side root


def test_trace_spans_actor_hop(ray_cluster):
    """driver -> actor method -> task submitted from the actor."""
    @ray_tpu.remote
    def from_actor():
        return 1

    @ray_tpu.remote
    class A:
        def call(self):
            return ray_tpu.get(from_actor.remote())

    a = A.remote()
    assert ray_tpu.get(a.call.remote(), timeout=60) == 1
    evs = _events_by_name(["call", "from_actor"])
    c, f = evs["call"], evs["from_actor"]
    assert c["trace_id"]
    assert f["trace_id"] == c["trace_id"]
    assert f["parent_span_id"] == c["span_id"]


def test_separate_roots_get_separate_traces(ray_cluster):
    @ray_tpu.remote
    def t_a():
        return None

    @ray_tpu.remote
    def t_b():
        return None

    ray_tpu.get([t_a.remote(), t_b.remote()], timeout=60)
    evs = _events_by_name(["t_a", "t_b"])
    assert evs["t_a"]["trace_id"] != evs["t_b"]["trace_id"]
