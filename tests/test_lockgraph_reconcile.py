"""Static<->runtime lock-graph reconciliation.

raylint's ``--emit-lock-graph`` models the project's lock-order graph
from source; ``lockdep.witnessed_graph()`` records the edges that
actually executed. Every runtime edge whose endpoints the static
registry knows must appear in the static graph — a missing edge means
the static pass has a resolution blind spot (dynamic dispatch, a
callback registration, a lock reached through a path ``resolve`` can't
follow), which is exactly the drift this test exists to catch before it
becomes a missed inversion.

The inverse direction is NOT asserted: the static graph legitimately
contains edges no single test run executes.

One edge class is allowlisted below rather than resolved: a
closure-local lock held across a call to a higher-order *parameter*
(``lazy_metrics``'s guard lock around ``factory()``). The call graph
deliberately does not attribute nested-closure bodies to their definer
(defining a callback is not calling it), and the callee of a bare
parameter is call-site-dependent — both sides of that edge are
statically invisible by design, not by accident. The allowlist names
the lock ids, so any OTHER missing edge still fails.

This module is in conftest.LOCKDEP_MODULES, so the runtime witness is
recording while the workload drives init/tasks/actors/get/shutdown.
"""

import pytest

import ray_tpu
from ray_tpu._private import lockdep
from ray_tpu._private.lint import core
from ray_tpu._private.lint.callgraph import emit_lock_graph


def _static_graph():
    project = core.Project(core.collect_sources())
    return emit_lock_graph(project)


# (outer lid, inner lid) pairs the static pass cannot see — see the
# module docstring. Keyed by registry lock ids (stable across line
# drift); only exact pairs are excused.
KNOWN_BLIND_SPOTS = {
    # lazy_metrics' closure guard held across factory() registering
    # metrics under the registry lock.
    ("ray_tpu.util.metrics.lock", "ray_tpu.util.metrics._registry_lock"),
}


def _drive_workload():
    """Exercise the lock-heavy control-plane paths: scheduling, actor
    lifecycle, object transfer, completion ingestion, shutdown."""

    @ray_tpu.remote
    def square(x):
        return x * x

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, k):
            self.n += k
            return self.n

    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        refs = [square.remote(i) for i in range(8)]
        assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(8)]
        c = Counter.remote()
        outs = [c.add.remote(1) for _ in range(4)]
        assert ray_tpu.get(outs[-1], timeout=60) == 4
        obj = ray_tpu.put(list(range(32)))
        assert ray_tpu.get(obj, timeout=60)[-1] == 31
    finally:
        ray_tpu.shutdown()


def test_runtime_edges_subset_of_static_graph():
    assert lockdep.installed(), "conftest should have installed lockdep"
    lockdep.reset()
    try:
        _drive_workload()
        witnessed = lockdep.witnessed_graph()
    finally:
        # Leave a clean graph for whatever module runs next either way.
        violations = lockdep.take_violations()
        lockdep.reset()
    assert not violations, violations
    assert witnessed, "workload drove the control plane; expected edges"

    static = _static_graph()
    site_to_lids = {}
    for lid, info in static["locks"].items():
        site_to_lids.setdefault(info["site"], set()).add(lid)
    static_edges = {(e["outer"], e["inner"]) for e in static["edges"]}

    missing = []
    mapped = 0
    for e in witnessed:
        outers = site_to_lids.get(e["held"], set())
        inners = site_to_lids.get(e["acquired"], set())
        if not outers or not inners:
            # A lock the static registry doesn't model (e.g. created via
            # an alias it can't attribute): out of reconciliation scope.
            continue
        mapped += 1
        if all((lo, li) in KNOWN_BLIND_SPOTS
               for lo in outers for li in inners):
            continue
        if not any((lo, li) in static_edges
                   for lo in outers for li in inners):
            missing.append(
                f"runtime edge {e['held']} -> {e['acquired']} "
                f"(witnessed at {e['site']}) has no static counterpart")
    assert mapped, (
        "no runtime edge mapped onto the static registry — the "
        "creation-site keys have drifted apart")
    assert not missing, (
        "static lock graph is missing runtime-witnessed edges "
        "(resolution blind spot — fix callgraph.resolve or the lock "
        "registry):\n" + "\n".join(missing))


def test_static_graph_covers_registry_locks():
    """Sanity on the static side alone: the export is well-formed and
    its edges only reference locks the registry knows (or the
    site-scoped ``?ambiguous`` identities)."""
    static = _static_graph()
    assert static["version"] == 1
    known = set(static["locks"])
    for e in static["edges"]:
        for end in (e["outer"], e["inner"]):
            assert end in known or end.startswith("?"), e
        assert e["chain"], e
