"""Metrics API + dashboard REST tests."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_counter_gauge_histogram():
    c = metrics.Counter("test_requests_total", "requests",
                        tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(-1)

    g = metrics.Gauge("test_temperature", "temp")
    g.set(21.5)

    h = metrics.Histogram("test_latency_seconds", "latency",
                          boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    samples = metrics.collect_samples()
    by_name = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
    assert any(s["value"] == 3.0 and s["tags"] == {"route": "/a"}
               for s in by_name["test_requests_total"])
    assert by_name["test_temperature"][0]["value"] == 21.5
    buckets = {s["tags"]["le"]: s["value"]
               for s in by_name["test_latency_seconds_bucket"]}
    assert buckets["0.1"] == 1 and buckets["1.0"] == 2
    assert buckets["+Inf"] == 3
    assert by_name["test_latency_seconds_count"][0]["value"] == 3

    text = metrics.prometheus_text([samples])
    assert '# TYPE test_requests_total counter' in text
    assert 'test_requests_total{route="/a"} 3.0' in text


def test_metrics_report_to_gcs(ray_cluster):
    g = metrics.Gauge("test_reported_gauge", "x")
    g.set(7.0)
    assert metrics.report_to_gcs()
    from ray_tpu._private import worker as worker_mod

    groups = worker_mod.require_worker().gcs.request("get_metrics")
    flat = [s for grp in groups for s in grp]
    assert any(s["name"] == "test_reported_gauge" and s["value"] == 7.0
               for s in flat)


def test_dashboard_rest(ray_cluster):
    from ray_tpu.dashboard import start_dashboard

    _actor, port = start_dashboard(port=18265)

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=15) as r:
            return r.read().decode()

    nodes = json.loads(get("/api/nodes"))
    assert len(nodes) == 1 and nodes[0]["Alive"]

    status = json.loads(get("/api/cluster_status"))
    assert status["total"]["CPU"] == 4.0

    html = get("/")
    assert "ray_tpu" in html

    prom = get("/metrics")
    assert "ray_tpu_cluster_nodes_alive 1" in prom
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4.0' in prom


def test_node_hardware_reporter(ray_cluster):
    """Per-node reporter samples (reference: reporter_agent.py:253) flow
    heartbeat -> GCS -> nodes API + /metrics gauges."""
    import time as _t

    from ray_tpu.dashboard import start_dashboard

    deadline = _t.time() + 15
    hw = {}
    while _t.time() < deadline:
        nodes = ray_tpu.nodes()
        hw = (nodes[0].get("Hardware") or {}) if nodes else {}
        if hw.get("store_capacity_bytes"):
            break
        _t.sleep(0.3)
    assert hw.get("store_capacity_bytes"), hw
    assert hw.get("mem_total_bytes")
    assert "tpu_chips_free" in hw and "workers" in hw

    try:
        _actor, port = start_dashboard(port=18266)
    except Exception:
        port = 18265   # test_dashboard_rest already started one
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    assert "ray_tpu_node_store_capacity_bytes" in text
    assert "ray_tpu_node_mem_total_bytes" in text
    # Pin accounting + device staging ride the same heartbeat sample
    # (store.cpp rtpu_stats_ex -> NM hw -> /metrics gauges).
    assert "ray_tpu_node_store_pinned_objects" in text
    assert "ray_tpu_node_store_pinned_bytes" in text
    assert "ray_tpu_node_device_staged_bytes_total" in text


def test_scheduler_counters_in_prometheus(ray_cluster):
    """Local-first scheduler counters (grants / spillbacks) ride the NM
    heartbeat's hardware sample into the GCS nodes view and surface as
    Prometheus counters on /metrics."""
    import time as _t

    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(8)])   # force local grants
    deadline = _t.time() + 15
    hw = {}
    while _t.time() < deadline:   # next heartbeat carries the counters
        nodes = ray_tpu.nodes()
        hw = (nodes[0].get("Hardware") or {}) if nodes else {}
        if hw.get("sched_local_grants_total"):
            break
        _t.sleep(0.3)
    assert hw.get("sched_local_grants_total"), hw
    assert "sched_spillbacks_total" in hw

    try:
        _actor, port = start_dashboard(port=18267)
    except Exception:
        port = 18265   # an earlier test already started one
    # The driver-side grant-latency histogram reaches /metrics through
    # the metrics reporter -> GCS metrics table path; push one sample
    # batch deterministically instead of waiting for the 5 s loop.
    from ray_tpu.util import metrics as metrics_mod
    assert metrics_mod.report_to_gcs()

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    assert "scheduler_local_grants_total" in text
    assert "scheduler_spillbacks_total" in text
    assert "scheduler_lease_grant_latency_seconds_bucket" in text
    assert 'source="local"' in text
