"""Metrics API + dashboard REST tests."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import metrics


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_counter_gauge_histogram():
    c = metrics.Counter("test_requests_total", "requests",
                        tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2.0, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    with pytest.raises(ValueError):
        c.inc(-1)

    g = metrics.Gauge("test_temperature", "temp")
    g.set(21.5)

    h = metrics.Histogram("test_latency_seconds", "latency",
                          boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)

    samples = metrics.collect_samples()
    by_name = {}
    for s in samples:
        by_name.setdefault(s["name"], []).append(s)
    assert any(s["value"] == 3.0 and s["tags"] == {"route": "/a"}
               for s in by_name["test_requests_total"])
    assert by_name["test_temperature"][0]["value"] == 21.5
    buckets = {s["tags"]["le"]: s["value"]
               for s in by_name["test_latency_seconds_bucket"]}
    assert buckets["0.1"] == 1 and buckets["1.0"] == 2
    assert buckets["+Inf"] == 3
    assert by_name["test_latency_seconds_count"][0]["value"] == 3

    text = metrics.prometheus_text([samples])
    assert '# TYPE test_requests_total counter' in text
    assert 'test_requests_total{route="/a"} 3.0' in text


def test_metrics_report_to_gcs(ray_cluster):
    g = metrics.Gauge("test_reported_gauge", "x")
    g.set(7.0)
    assert metrics.report_to_gcs()
    from ray_tpu._private import worker as worker_mod

    groups = worker_mod.require_worker().gcs.request("get_metrics")
    flat = [s for grp in groups for s in grp]
    assert any(s["name"] == "test_reported_gauge" and s["value"] == 7.0
               for s in flat)


def test_dashboard_rest(ray_cluster):
    from ray_tpu.dashboard import start_dashboard

    _actor, port = start_dashboard(port=18265)

    def get(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=15) as r:
            return r.read().decode()

    nodes = json.loads(get("/api/nodes"))
    assert len(nodes) == 1 and nodes[0]["Alive"]

    status = json.loads(get("/api/cluster_status"))
    assert status["total"]["CPU"] == 4.0

    html = get("/")
    assert "ray_tpu" in html

    prom = get("/metrics")
    assert "ray_tpu_cluster_nodes_alive 1" in prom
    assert 'ray_tpu_cluster_resource_total{resource="CPU"} 4.0' in prom

    # Per-node agent surfaces behind the head: log listing + in-band
    # stacks (reference: dashboard log/reporter agent REST).
    logs = json.loads(get("/api/logs?list=1"))
    assert logs and logs[0]["workers"]
    stacks = json.loads(get("/api/stacks?timeout_s=5"))
    assert stacks and stacks[0]["node_manager"]["threads"]
    assert isinstance(stacks[0]["workers"], list)


def test_node_hardware_reporter(ray_cluster):
    """Per-node reporter samples (reference: reporter_agent.py:253) flow
    heartbeat -> GCS -> nodes API + /metrics gauges."""
    import time as _t

    from ray_tpu.dashboard import start_dashboard

    deadline = _t.time() + 15
    hw = {}
    while _t.time() < deadline:
        nodes = ray_tpu.nodes()
        hw = (nodes[0].get("Hardware") or {}) if nodes else {}
        if hw.get("store_capacity_bytes"):
            break
        _t.sleep(0.3)
    assert hw.get("store_capacity_bytes"), hw
    assert hw.get("mem_total_bytes")
    assert "tpu_chips_free" in hw and "workers" in hw

    try:
        _actor, port = start_dashboard(port=18266)
    except Exception:
        port = 18265   # test_dashboard_rest already started one
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    assert "ray_tpu_node_store_capacity_bytes" in text
    assert "ray_tpu_node_mem_total_bytes" in text
    # Pin accounting + device staging ride the same heartbeat sample
    # (store.cpp rtpu_stats_ex -> NM hw -> /metrics gauges).
    assert "ray_tpu_node_store_pinned_objects" in text
    assert "ray_tpu_node_store_pinned_bytes" in text
    assert "ray_tpu_node_device_staged_bytes_total" in text


def test_scheduler_counters_in_prometheus(ray_cluster):
    """Local-first scheduler counters (grants / spillbacks) ride the NM
    heartbeat's hardware sample into the GCS nodes view and surface as
    Prometheus counters on /metrics."""
    import time as _t

    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(8)])   # force local grants
    deadline = _t.time() + 15
    hw = {}
    while _t.time() < deadline:   # next heartbeat carries the counters
        nodes = ray_tpu.nodes()
        hw = (nodes[0].get("Hardware") or {}) if nodes else {}
        if hw.get("sched_local_grants_total"):
            break
        _t.sleep(0.3)
    assert hw.get("sched_local_grants_total"), hw
    assert "sched_spillbacks_total" in hw

    try:
        _actor, port = start_dashboard(port=18267)
    except Exception:
        port = 18265   # an earlier test already started one
    # The driver-side grant-latency histogram reaches /metrics through
    # the metrics reporter -> GCS metrics table path; push one sample
    # batch deterministically instead of waiting for the 5 s loop.
    from ray_tpu.util import metrics as metrics_mod
    assert metrics_mod.report_to_gcs()

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=15).read().decode()
    assert "scheduler_local_grants_total" in text
    assert "scheduler_spillbacks_total" in text
    assert "scheduler_lease_grant_latency_seconds_bucket" in text
    assert 'source="local"' in text


# ------------------------------------ multi-process /metrics aggregation


def test_prometheus_multiprocess_aggregation():
    """Counters from different processes SUM; gauges tagged per replica
    do not collide; histogram buckets stay cumulative and each family's
    series stay contiguous (Prometheus rejects interleaved families)."""
    group_a = [
        {"name": "agg_requests_total", "tags": {}, "value": 3.0,
         "kind": "counter", "help": "req"},
        {"name": "agg_depth", "tags": {"replica": "a"}, "value": 5.0,
         "kind": "gauge", "help": "depth"},
        {"name": "agg_lat_bucket", "tags": {"le": "0.1"}, "value": 1,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_bucket", "tags": {"le": "+Inf"}, "value": 2,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_sum", "tags": {}, "value": 0.3,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_count", "tags": {}, "value": 2,
         "kind": "histogram", "help": "lat"},
    ]
    group_b = [
        {"name": "agg_requests_total", "tags": {}, "value": 4.0,
         "kind": "counter", "help": "req"},
        {"name": "agg_depth", "tags": {"replica": "b"}, "value": 7.0,
         "kind": "gauge", "help": "depth"},
        {"name": "agg_lat_bucket", "tags": {"le": "0.1"}, "value": 2,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_bucket", "tags": {"le": "+Inf"}, "value": 3,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_sum", "tags": {}, "value": 0.9,
         "kind": "histogram", "help": "lat"},
        {"name": "agg_lat_count", "tags": {}, "value": 3,
         "kind": "histogram", "help": "lat"},
    ]
    # A same-tag gauge from a later process takes last-write, not sum.
    group_c = [
        {"name": "agg_depth", "tags": {"replica": "b"}, "value": 9.0,
         "kind": "gauge", "help": "depth"},
    ]
    text = metrics.prometheus_text([group_a, group_b, group_c])
    lines = text.splitlines()

    assert "agg_requests_total 7.0" in text           # counters sum
    assert 'agg_depth{replica="a"} 5.0' in text       # no collision
    assert 'agg_depth{replica="b"} 9.0' in text       # last write wins
    assert 'agg_lat_bucket{le="0.1"} 3' in text       # buckets sum...
    assert 'agg_lat_bucket{le="+Inf"} 5' in text      # ...stay cumulative
    assert "agg_lat_sum 1.2" in text
    assert "agg_lat_count 5" in text

    # Families are contiguous: every series line between a family's
    # # HELP header and the next # HELP belongs to that family.
    family = None
    seen_done = set()
    for ln in lines:
        if ln.startswith("# HELP "):
            nxt = ln.split()[2]
            assert nxt not in seen_done, f"family {nxt} interleaved"
            if family is not None:
                seen_done.add(family)
            family = nxt
        elif ln.startswith("# TYPE ") or not ln:
            continue
        else:
            name = ln.split("{")[0].split(" ")[0]
            base = name.removesuffix("_bucket").removesuffix(
                "_sum").removesuffix("_count")
            assert base == family, f"{ln} outside family {family}"


def test_multiprocess_counters_sum_on_metrics_endpoint(ray_cluster):
    """Live cross-process check: two replica actors register the same
    counter/gauge names; the aggregated exposition sums the counters and
    keeps the per-replica gauge series apart."""
    @ray_tpu.remote
    class Replica:
        def __init__(self, tag, inc):
            from ray_tpu.util import metrics as m

            self._c = m.Counter("mp_agg_requests_total", "reqs")
            self._g = m.Gauge("mp_agg_depth", "depth",
                              tag_keys=("replica",))
            self._c.inc(inc)
            self._g.set(inc, tags={"replica": tag})

        def push(self):
            from ray_tpu.util import metrics as m

            return m.report_to_gcs()

    a = Replica.remote("ra", 2.0)
    b = Replica.remote("rb", 5.0)
    assert ray_tpu.get([a.push.remote(), b.push.remote()], timeout=30) \
        == [True, True]

    from ray_tpu._private import worker as worker_mod

    w = worker_mod.require_worker()
    import time as _t

    deadline = _t.time() + 15
    while _t.time() < deadline:
        groups = w.gcs.request("get_metrics")
        text = metrics.prometheus_text(groups)
        if "mp_agg_requests_total 7.0" in text:
            break
        _t.sleep(0.3)
    assert "mp_agg_requests_total 7.0" in text, text
    assert 'mp_agg_depth{replica="ra"} 2.0' in text
    assert 'mp_agg_depth{replica="rb"} 5.0' in text


# --------------------------------------------------- README docs drift


def _registered_metric_names():
    """Every metric name registered in ray_tpu/: constructor literals
    (Counter/Gauge/Histogram first args) plus the dashboard head's
    builtin gauge/counter names."""
    import ast
    import pathlib
    import re

    root = pathlib.Path(ray_tpu.__file__).parent
    names = set()
    for path in root.rglob("*.py"):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            base = fn.attr if isinstance(fn, ast.Attribute) else \
                getattr(fn, "id", "")
            if base in ("Counter", "Gauge", "Histogram") and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                names.add(node.args[0].value)
    head = (root / "dashboard" / "head.py").read_text()
    names |= set(re.findall(r'"((?:ray_tpu|scheduler)_[a-z0-9_]+)"',
                            head))
    return names


def test_readme_metric_table_covers_registered_metrics():
    """Docs-drift guard (ISSUE 8 satellite): every metric name the code
    registers must appear in the README's Observability metric table."""
    import pathlib

    readme = (pathlib.Path(ray_tpu.__file__).parent.parent /
              "README.md").read_text()
    names = _registered_metric_names()
    assert names, "metric-name scan found nothing — scanner broken?"
    missing = sorted(n for n in names if n not in readme)
    assert not missing, (
        f"metrics registered in ray_tpu/ but missing from the README "
        f"Observability table: {missing}")
