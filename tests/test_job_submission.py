"""Job submission tests: real driver subprocesses against the cluster."""

import sys

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture(scope="module")
def client():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield JobSubmissionClient()
    ray_tpu.shutdown()


def test_submit_and_succeed(client, tmp_path):
    script = tmp_path / "job.py"
    script.write_text(
        "import os, sys\n"
        "sys.path.insert(0, '/root/repo')\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import ray_tpu\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def f(x):\n"
        "    return x * 2\n"
        "print('result:', ray_tpu.get(f.remote(21)))\n"
        "ray_tpu.shutdown()\n")
    sid = client.submit_job(
        entrypoint=f"{sys.executable} {script}",
        runtime_env={"env_vars": {"JAX_PLATFORMS": "cpu",
                                  "PALLAS_AXON_POOL_IPS": ""}})
    status = client.wait_until_finish(sid, timeout=120)
    logs = client.get_job_logs(sid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "result: 42" in logs
    info = client.get_job_info(sid)
    assert info["return_code"] == 0


def test_failed_job(client):
    sid = client.submit_job(entrypoint=f"{sys.executable} -c 'exit(3)'")
    assert client.wait_until_finish(sid, timeout=60) == JobStatus.FAILED
    assert client.get_job_info(sid)["return_code"] == 3


def test_stop_job(client):
    sid = client.submit_job(
        entrypoint=f"{sys.executable} -c 'import time; time.sleep(300)'")
    assert client.get_job_status(sid) == JobStatus.RUNNING
    assert client.stop_job(sid)
    status = client.wait_until_finish(sid, timeout=30)
    assert status == JobStatus.STOPPED


def test_list_jobs(client):
    jobs = client.list_jobs()
    assert len(jobs) >= 3
    assert all("submission_id" in j for j in jobs)
