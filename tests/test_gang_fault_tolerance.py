"""Gang fault tolerance: slice-death detection, collective poisoning, and
checkpointed gang restart — the TPU-first flagship scenario (SURVEY §7(c),
ROADMAP "Mid-step gang failure").

The gang is the failure domain: one dead `xla_dist` rank invalidates the
whole mesh (on a TPU pod, one dead host kills the slice). These tests
SIGKILL one rank mid-step and prove, end to end:

- bounded-time detection (supervisor heartbeat + GCS actor-death push,
  NOT the old hardcoded 300 s collective deadline),
- survivor unwedge (the poisoned collective raises GangMemberDiedError),
- gang re-formation under a fresh group name + placement group,
- resume from the latest persisted checkpoint with a correct final result,
- restart/poison counters on the dashboard's /metrics.

Every wait in this file is deadline-driven (no unbounded get): a
regression in detection fails fast instead of hanging the suite.
"""

import json
import os
import signal
import threading
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu
from ray_tpu import exceptions
from ray_tpu._private.config import config
from ray_tpu.train import (
    Checkpoint, FailureConfig, JaxTrainer, RunConfig, ScalingConfig,
)

HEARTBEAT_S = 1.0       # RAY_TPU_GANG_HEARTBEAT_S for these tests
DETECT_BOUND_S = 2 * HEARTBEAT_S + 3.0   # 2x heartbeat + CI slack


@pytest.fixture
def gang_cluster():
    old = {k: config.get(k)
           for k in ("gang_heartbeat_s", "gang_restart_backoff_s")}
    config.set("gang_heartbeat_s", HEARTBEAT_S)
    config.set("gang_restart_backoff_s", 0.1)
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()
    for k, v in old.items():
        config.set(k, v)


def _fit_bounded(trainer, timeout_s):
    """fit() under a hard deadline — the suite must fail fast, not hang,
    if detection/restart regresses."""
    out = {}

    def run():
        try:
            out["result"] = trainer.fit()
        except BaseException as e:   # surfaced below
            out["error"] = e

    th = threading.Thread(target=run, daemon=True, name="fit-bounded")
    th.start()
    th.join(timeout_s)
    assert out, f"fit() exceeded its {timeout_s}s deadline (wedged?)"
    if "error" in out:
        raise out["error"]
    return out["result"]


def _wait_for(pred, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _gang_loop(cfg):
    """Per-step compiled allreduce over the gang (xla_dist); rank 0
    checkpoints every step. Writes side-channel files the test uses to
    find rank pids and to record the survivor's unwedge latency."""
    import os
    import time

    import numpy as np

    from ray_tpu import train
    from ray_tpu.parallel import collective
    from ray_tpu.train import Checkpoint

    side = cfg["side_dir"]
    sess = train.session._get_session()
    g = collective.get_group(sess.collective_group_name)
    rank = train.get_world_rank()

    start_step = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start_step = ckpt.to_dict()["step"] + 1

    tmp = os.path.join(side, f"rank{rank}.pid.tmp")
    with open(tmp, "w") as f:
        f.write(str(os.getpid()))
    os.replace(tmp, os.path.join(side, f"rank{rank}.pid"))

    for step in range(start_step, cfg["steps"]):
        # Asymmetric pacing: rank 0 enters the collective immediately and
        # blocks there while the other ranks "compute" (sleep) — so a
        # SIGKILL of rank 1 lands while the survivor is INSIDE the
        # compiled step, the scenario the poison path must unwedge.
        if rank != 0:
            time.sleep(cfg["step_s"])
        t_op = time.time()
        try:
            out = g.allreduce(np.full((4,), float(rank + 1), np.float32))
        except BaseException as e:
            # Record how long the survivor sat in the failed collective
            # (the unwedge bound the flagship asserts on).
            with open(os.path.join(side, f"unwedge_rank{rank}"), "w") as f:
                f.write(f"{type(e).__name__}:{time.time() - t_op:.3f}")
            raise
        if rank == 0:
            train.report(
                {"step": step,
                 "allreduce0": float(np.asarray(out).ravel()[0])},
                checkpoint=Checkpoint.from_dict({"step": step}))


def _run_dir_has_checkpoint(run_dir):
    try:
        return any(d.startswith("checkpoint_") for d in os.listdir(run_dir))
    except OSError:
        return False


def test_sigkill_one_rank_mid_step_recovers(gang_cluster, tmp_path):
    """The flagship: SIGKILL one xla_dist rank during the stepped run;
    the survivor unwedges within ~2x the gang heartbeat, the gang
    re-forms, training resumes from the latest checkpoint, and the final
    result is correct with >=1 recorded restart."""
    side = str(tmp_path / "side")
    os.makedirs(side, exist_ok=True)
    steps = 8
    run_dir = str(tmp_path / "gangkill")

    record = {}

    def killer():
        # Wait for rank 1's pid AND one persisted checkpoint (so there is
        # something to resume from), then SIGKILL rank 1 mid-run. Kill
        # exactly once: the re-formed gang must survive.
        pid_path = os.path.join(side, "rank1.pid")
        deadline = time.time() + 60
        while time.time() < deadline:
            if os.path.exists(pid_path) and _run_dir_has_checkpoint(run_dir):
                try:
                    pid = int(open(pid_path).read())
                except (OSError, ValueError):
                    time.sleep(0.05)
                    continue
                record["t_kill"] = time.time()
                os.kill(pid, signal.SIGKILL)
                return
            time.sleep(0.05)
        record["error"] = "killer never found a target"

    trainer = JaxTrainer(
        _gang_loop,
        train_loop_config={"side_dir": side, "steps": steps,
                           "step_s": 0.3},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="gangkill", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2)),
    )
    kth = threading.Thread(target=killer, daemon=True)
    kth.start()
    result = _fit_bounded(trainer, timeout_s=180)
    t_done = time.time()

    assert "t_kill" in record, record.get("error", "kill never happened")
    # Recovery end to end: the run finished despite the mid-step SIGKILL.
    assert result.ok, result.error
    assert result.num_restarts >= 1
    assert any("GangMemberDied" in r for r in result.restart_reasons), \
        result.restart_reasons
    # Kill-to-done is bounded nowhere near the old 300 s deadline.
    assert t_done - record["t_kill"] < 120

    # Correctness: every reported step saw the full-gang allreduce (1+2),
    # the final step completed, and the restart resumed from a checkpoint
    # (no step before the resume point was recomputed more than the
    # checkpoint lag allows).
    hist = result.metrics_history
    assert hist and all(m["allreduce0"] == 3.0 for m in hist)
    assert hist[-1]["step"] == steps - 1
    assert {m["step"] for m in hist} == set(range(steps))
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["step"] == steps - 1

    # Survivor unwedge: rank 0 raised GangMemberDiedError out of the
    # poisoned/severed collective within the detection bound — not the
    # collective op deadline.
    unwedge = os.path.join(side, "unwedge_rank0")
    assert os.path.exists(unwedge), \
        "survivor never recorded an unwedge (killed while idle?)"
    err_name, elapsed = open(unwedge).read().split(":")
    assert err_name == "GangMemberDiedError", err_name
    # The survivor entered the collective up to one step before the kill;
    # everything past that is detection/unwedge latency.
    assert float(elapsed) <= 0.3 + DETECT_BOUND_S + 0.3, \
        f"survivor sat {elapsed}s in the dead collective"

    # Detection latency (supervisor heartbeat) was observed and bounded.
    from ray_tpu.util import metrics

    samples = {s["name"]: s for s in metrics.collect_samples()}
    assert samples["train_gang_restarts_total"]["value"] >= 1
    assert samples["gang_poisoned_total"]["value"] >= 1
    assert samples["gang_time_to_detection_seconds_count"]["value"] >= 1
    assert samples["gang_time_to_detection_seconds_sum"]["value"] \
        <= DETECT_BOUND_S * \
        samples["gang_time_to_detection_seconds_count"]["value"]

    # Observability: the counters flow to the dashboard's /metrics.
    assert metrics.report_to_gcs()
    from ray_tpu.dashboard import start_dashboard

    _actor, port = start_dashboard(port=18277)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=15) as r:
        text = r.read().decode()
    assert "train_gang_restarts_total" in text
    assert "gang_poisoned_total" in text
    assert "gang_time_to_detection_seconds" in text


def test_poison_unwedges_pending_collective(gang_cluster):
    """Collective poisoning in isolation: a rank pending in a store-backend
    collective (its peer never shows up) raises GangMemberDiedError within
    ~2x the gang heartbeat of the group being poisoned — it does NOT wait
    out the collective op deadline."""
    from ray_tpu.parallel import collective

    g = collective.init_collective_group(
        2, 0, backend="store", group_name="poison_unit")
    res = {}

    def run():
        t0 = time.time()
        try:
            g.barrier()
            res["err"] = None
        except BaseException as e:
            res["err"] = e
            res["elapsed"] = time.time() - t0

    th = threading.Thread(target=run, daemon=True)
    th.start()
    time.sleep(0.5)   # the barrier is now pending (rank 1 never joins)
    t_poison = time.time()
    assert collective.poison_group("poison_unit",
                                   "rank 1 SIGKILLed (test)")
    th.join(DETECT_BOUND_S + 2)
    assert not th.is_alive(), \
        "poisoned collective still pending past the detection bound"
    assert isinstance(res["err"], exceptions.GangMemberDiedError)
    assert time.time() - t_poison <= DETECT_BOUND_S + 2
    assert "SIGKILLed" in str(res["err"])
    collective.destroy_collective_group("poison_unit")


def _poll_gang_loop(cfg=None):
    import time

    from ray_tpu import train

    for i in range(1200):
        time.sleep(0.05)
        if train.get_world_rank() == 0 and i % 20 == 0:
            train.report({"i": i})


def test_worker_group_poll_isolates_dead_rank(gang_cluster):
    """poll() hardening: a dead rank surfaces as state='dead' instead of
    one RayActorError aborting the whole poll batch, and the supervisor
    records a gang error (poisoning the group) within a bounded time."""
    from ray_tpu.train.worker_group import WorkerGroup

    group = WorkerGroup(2, {"CPU": 1}, backend="store",
                        group_name="pollgang", experiment_name="pg")
    try:
        group.start(_poll_gang_loop, None, None)
        states = group.poll()          # healthy: no raise, all running
        assert [s["state"] for s in states] == ["running", "running"]

        ray_tpu.kill(group.workers[1])
        deadline = time.time() + 15
        while time.time() < deadline:
            states = group.poll()      # must never raise
            if states[1]["state"] == "dead":
                break
            time.sleep(0.2)
        assert states[1]["state"] == "dead", states
        assert states[0]["state"] == "running", states

        _wait_for(lambda: group.gang_error is not None,
                  timeout=DETECT_BOUND_S + 5,
                  msg="supervisor to record the gang error")
        assert isinstance(group.gang_error, exceptions.GangMemberDiedError)
        assert group.gang_error.rank == 1
    finally:
        group.shutdown(graceful=False)


@pytest.mark.slow
def test_chaos_gang_killer_sweep(tmp_path):
    """NodeKiller-style chaos sweep: random gang-rank SIGKILLs during a
    short JaxTrainer.fit() run; the trainer must keep re-forming from
    checkpoints and finish correctly."""
    old = {k: config.get(k)
           for k in ("gang_heartbeat_s", "gang_restart_backoff_s")}
    config.set("gang_heartbeat_s", HEARTBEAT_S)
    config.set("gang_restart_backoff_s", 0.1)
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        side = str(tmp_path / "side")
        os.makedirs(side, exist_ok=True)
        steps = 10
        run_dir = str(tmp_path / "gangchaos")
        stop = threading.Event()
        kills = []

        def killer():
            import random

            rng = random.Random(0)
            killed_pids = set()
            deadline = time.time() + 240
            while (not stop.is_set() and len(kills) < 2
                   and time.time() < deadline):
                if not _run_dir_has_checkpoint(run_dir):
                    time.sleep(0.1)
                    continue
                rank = rng.choice([0, 1])
                path = os.path.join(side, f"rank{rank}.pid")
                try:
                    pid = int(open(path).read())
                except (OSError, ValueError):
                    time.sleep(0.1)
                    continue
                if pid in killed_pids:   # wait for the re-formed gang
                    time.sleep(0.2)
                    continue
                killed_pids.add(pid)
                kills.append((rank, pid, time.time()))
                try:
                    os.kill(pid, signal.SIGKILL)
                except OSError:
                    pass
                time.sleep(3.0)   # let the gang re-form and progress

        trainer = JaxTrainer(
            _gang_loop,
            train_loop_config={"side_dir": side, "steps": steps,
                               "step_s": 0.25},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(
                name="gangchaos", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=6)),
        )
        kth = threading.Thread(target=killer, daemon=True)
        kth.start()
        try:
            result = _fit_bounded(trainer, timeout_s=420)
        finally:
            stop.set()
        assert result.ok, result.error
        assert kills, "chaos killer never fired"
        assert result.num_restarts >= 1
        hist = result.metrics_history
        assert hist[-1]["step"] == steps - 1
        assert all(m["allreduce0"] == 3.0 for m in hist)
    finally:
        ray_tpu.shutdown()
        for k, v in old.items():
            config.set(k, v)
