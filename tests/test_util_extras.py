"""Tests for util extras: multiprocessing Pool shim, check_serialize,
usage stats (reference analogs: util/multiprocessing/pool.py,
util/check_serialize.py, _private/usage/usage_lib.py)."""

import threading

import pytest

import ray_tpu
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.check_serialize import inspect_serializability


def _sq(x):
    return x * x


def test_pool_map(ray_start_regular):
    with Pool(2) as p:
        assert p.map(_sq, range(10)) == [x * x for x in range(10)]


def test_pool_apply_and_async(ray_start_regular):
    with Pool(2) as p:
        assert p.apply(_sq, (7,)) == 49
        r = p.apply_async(_sq, (8,))
        assert r.get(timeout=30) == 64
        assert r.ready() and r.successful()


def test_pool_starmap_imap(ray_start_regular):
    with Pool(2) as p:
        assert p.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]
        assert list(p.imap(_sq, range(6), chunksize=2)) == \
            [x * x for x in range(6)]
        assert sorted(p.imap_unordered(_sq, range(6), chunksize=2)) == \
            sorted(x * x for x in range(6))


def test_pool_error_and_callbacks(ray_start_regular):
    def boom(x):
        raise ValueError("boom")

    with Pool(1) as p:
        r = p.apply_async(boom, (1,))
        with pytest.raises(Exception):
            r.get(timeout=30)
        assert not r.successful()

        got = []
        done = threading.Event()
        r2 = p.map_async(_sq, [1, 2, 3],
                         callback=lambda v: (got.append(v), done.set()))
        assert r2.get(timeout=30) == [1, 4, 9]
        assert done.wait(5) and got == [[1, 4, 9]]


def test_pool_initializer(ray_start_regular):
    def init_fn(v):
        import os
        os.environ["_POOL_INIT"] = str(v)

    def read_init(_):
        import os
        return os.environ.get("_POOL_INIT")

    with Pool(2, initializer=init_fn, initargs=(42,)) as p:
        assert p.map(read_init, range(4)) == ["42"] * 4


def test_pool_lifecycle(ray_start_regular):
    p = Pool(1)
    with pytest.raises(ValueError):
        p.join()  # not closed yet
    p.close()
    p.join()
    with pytest.raises(ValueError):
        p.map(_sq, [1])


def test_pool_join_waits_for_outstanding(ray_start_regular):
    """ADVICE r3: join() after close() must block until submitted work
    completes (stdlib semantics), not return immediately."""
    import time

    def slow(x):
        time.sleep(0.5)
        return x

    p = Pool(2)
    res = p.apply_async(slow, (1,))
    assert p._pending  # tracked while outstanding
    p.close()
    t0 = time.monotonic()
    p.join()
    assert time.monotonic() - t0 > 0.2  # actually waited
    assert res.get(timeout=5) == 1
    p.terminate()

    # Completed results are untracked by the AsyncResult collector itself
    # (no join involved), so a long-lived pool never pins dead results.
    p2 = Pool(1)
    r2 = p2.apply_async(slow, (2,))
    assert r2.get(timeout=5) == 2
    deadline = time.monotonic() + 5
    while p2._pending and time.monotonic() < deadline:
        time.sleep(0.02)  # collector thread calls on_done after get()
    assert not p2._pending
    # imap submits eagerly: un-iterated work is still visible to join().
    it = p2.imap(slow, [1, 2])
    assert p2._pending
    p2.close()
    p2.join()
    assert list(it) == [1, 2]
    p2.terminate()
    assert not p2._pending  # terminate drops dead work


def test_check_serialize_ok():
    ok, failures = inspect_serializability(lambda x: x + 1,
                                           print_failures=False)
    assert ok and not failures


def test_check_serialize_finds_capture():
    lock = threading.Lock()

    def f(x):
        with lock:
            return x

    ok, failures = inspect_serializability(f, print_failures=False)
    assert not ok
    assert any(t.name == "lock" for t in failures)


def test_usage_stats(ray_start_regular):
    from ray_tpu._private import usage
    import ray_tpu.train  # noqa: F401  (records library usage)

    usage.record_library_usage("train")
    usage.record_extra_usage_tag("test_tag", "on")
    stats = usage.get_usage_stats()
    assert stats is not None
    assert "train" in stats["libraries_used"]
    assert stats["extra_tags"].get("test_tag") == "on"
    path = usage.write_usage_report()
    assert path is not None
    import json
    with open(path) as f:
        assert json.load(f)["ray_tpu_version"]


def test_usage_stats_opt_out(ray_start_regular, monkeypatch):
    # The opt-out lives on the typed registry (knob usage_stats_enabled)
    # but the env contract survives: usage_stats_enabled() refreshes the
    # knob from RAY_TPU_USAGE_STATS_ENABLED whenever it is set.
    from ray_tpu._private import usage
    from ray_tpu._private.config import config
    try:
        monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "0")
        assert not usage.usage_stats_enabled()
        assert usage.write_usage_report() is None
        monkeypatch.setenv("RAY_TPU_USAGE_STATS_ENABLED", "1")
        assert usage.usage_stats_enabled()
    finally:
        # refresh_from_env persists the env value into the SHARED
        # registry; monkeypatch restores only the env — put the knob
        # back even when an assert above fails, or later tests inherit
        # a disabled-stats registry with a misleading failure.
        monkeypatch.delenv("RAY_TPU_USAGE_STATS_ENABLED", raising=False)
        config.set("usage_stats_enabled", True)
