"""Local-first task scheduling at the node manager with GCS spillback.

Reference behaviors under test: the hybrid local-first policy
(src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50) — a
caller's own node manager grants worker leases from its local
free-resource ledger; the GCS is informed asynchronously (``local_held``
riding heartbeats) and consulted synchronously only on spillback.
Covered here: the grant-vs-spillback decision matrix, revocation /
fairness backoff for locally-granted leases, the GCS resource-view
reconciliation (including after a node manager dies with outstanding
local grants), and the centralized A/B baseline with the toggle off.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private.config import config


@pytest.fixture
def local_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker()


def _nm():
    from ray_tpu._private import worker as worker_mod

    return worker_mod._global_cluster.nm


def _nm_request(payload, timeout=60):
    w = _worker()
    conn = w.nm_conn(w._own_nm_address())
    return conn.request(protocol.REQUEST_LOCAL_LEASE, payload,
                        timeout=timeout)


def _wait_for(pred, timeout=15, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_tasks_granted_locally(local_cluster):
    """The steady-state task path is served by the local scheduler: the
    driver's leases are local grants and the NM counters show it."""
    @ray_tpu.remote
    def pid():
        import os
        return os.getpid()

    pids = {ray_tpu.get(pid.remote()) for _ in range(10)}
    assert len(pids) == 1, pids
    nm = _nm()
    assert nm.local_grants_total >= 1
    lm = _worker()._lease_mgr
    leases = [l for st in lm._shapes.values() for l in st.leases]
    assert leases and all(l.local for l in leases)
    # The stats RPC every observer (microbench, tests) uses.
    stats = _worker().nm_conn(_worker()._own_nm_address()).request(
        protocol.SCHEDULER_STATS, {}, timeout=10)
    assert stats["local_grants_total"] >= 1
    assert stats["local_grants_open"] >= 1


def test_grant_vs_spillback_decision_matrix(local_cluster):
    """Fits-locally -> granted; too big / TPU-shaped / unknown custom
    resource -> declined (None reply = spill back to the GCS)."""
    nm = _nm()
    w = _worker()
    spill0 = nm.local_spillbacks_total
    grant = _nm_request({"client_id": w.client_id,
                         "resources": {"CPU": 1.0}})
    assert grant is not None
    assert grant["node_id"] == nm.node_id
    assert grant["lease_id"].startswith(b"nml:")
    assert grant["worker_id"] and grant["direct_address"]

    # Exceeds the node's capacity: decline.
    assert _nm_request({"client_id": w.client_id,
                        "resources": {"CPU": 64.0}}) is None
    # TPU shapes bind chips at spawn via the GCS path: decline.
    assert _nm_request({"client_id": w.client_id,
                        "resources": {"CPU": 1.0, "TPU": 1.0}}) is None
    # A custom resource this node doesn't have: decline.
    assert _nm_request({"client_id": w.client_id,
                        "resources": {"CPU": 1.0, "gadget": 1.0}}) is None
    assert nm.local_spillbacks_total >= spill0 + 3

    w.nm_conn(w._own_nm_address()).notify(
        protocol.RETURN_LOCAL_LEASE,
        {"lease_id": grant["lease_id"], "worker_id": grant["worker_id"]})
    _wait_for(lambda: nm._local_held.is_zero(), msg="ledger released")
    assert not nm._local_grants


def test_revoke_signal_backoff_then_recovers(local_cluster):
    """A GCS revoke_local_lease signal puts overlapping shapes on a
    fairness backoff (declined -> spilled back to the central queue);
    after the window the local path grants again."""
    nm = _nm()
    w = _worker()
    old_backoff = config.local_lease_backoff_s
    config.set("local_lease_backoff_s", 0.4)
    try:
        grant = _nm_request({"client_id": w.client_id,
                             "resources": {"CPU": 1.0}})
        assert grant is not None
        nm._on_revoke_local_lease({"demands": [{"CPU": 1.0}]})
        # Overlapping shape declines during the backoff window.
        assert _nm_request({"client_id": w.client_id,
                            "resources": {"CPU": 1.0}}) is None
        time.sleep(0.6)
        g2 = _nm_request({"client_id": w.client_id,
                          "resources": {"CPU": 1.0}})
        assert g2 is not None
        for g in (grant, g2):
            w.nm_conn(w._own_nm_address()).notify(
                protocol.RETURN_LOCAL_LEASE,
                {"lease_id": g["lease_id"], "worker_id": g["worker_id"]})
        _wait_for(lambda: nm._local_held.is_zero(), msg="ledger released")
    finally:
        config.set("local_lease_backoff_s", old_backoff)


def test_gcs_view_reconciles_local_grants(local_cluster):
    """Central placement sees local grants: available_resources() (the
    GCS's effective view) shrinks while a local grant holds capacity and
    recovers once it is returned — the async resource-delta loop."""
    nm = _nm()
    w = _worker()
    grant = _nm_request({"client_id": w.client_id,
                         "resources": {"CPU": 2.0}})
    assert grant is not None
    _wait_for(lambda: ray_tpu.available_resources().get("CPU") == 2.0,
              msg="GCS view to reflect the local grant")
    w.nm_conn(w._own_nm_address()).notify(
        protocol.RETURN_LOCAL_LEASE,
        {"lease_id": grant["lease_id"], "worker_id": grant["worker_id"]})
    _wait_for(lambda: ray_tpu.available_resources().get("CPU") == 4.0,
              msg="GCS view to recover after the return")
    assert nm._local_held.is_zero()


def test_local_lease_revocation_drains(local_cluster):
    """Revoking a locally-granted lease held by a real LeaseManager:
    the holder drains it, returns it to the NM, and the ledger frees —
    without the GCS ever brokering the lease."""
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(8)])
    nm = _nm()
    lm = _worker()._lease_mgr
    leases = [l for st in lm._shapes.values() for l in st.leases
              if l.local and not l.dead]
    assert leases
    held_before = dict(nm._local_held.to_dict())
    assert any(v > 0 for v in held_before.values())
    nm._on_revoke_local_lease({"demands": [{"CPU": 1.0}]})
    _wait_for(lambda: sum(nm._local_held.to_dict().values())
              < sum(held_before.values()),
              msg="a local grant to drain after revocation")


def test_nm_death_with_outstanding_local_grants():
    """A node manager dies while its local grants hold capacity: the GCS
    drops the node (grants die with it), the cluster view converges to
    the survivors, and scheduling keeps working."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    node2 = cluster.add_node(num_cpus=2)
    try:
        cluster.connect(object_store_memory=64 * 1024 * 1024)
        assert cluster.wait_for_nodes()
        w = _worker()
        conn = w.nm_conn(node2.address)
        grant = conn.request(protocol.REQUEST_LOCAL_LEASE,
                             {"client_id": w.client_id,
                              "resources": {"CPU": 1.0}}, timeout=60)
        assert grant is not None
        _wait_for(lambda: ray_tpu.available_resources().get("CPU") == 3.0,
                  msg="GCS view to reflect node2's local grant")
        cluster.remove_node(node2)   # dies holding the grant
        _wait_for(lambda: ray_tpu.available_resources().get("CPU", 0) == 2.0,
                  timeout=30, msg="GCS view to drop the dead node")

        @ray_tpu.remote
        def sq(x):
            return x * x

        assert ray_tpu.get([sq.remote(i) for i in range(4)],
                           timeout=60) == [0, 1, 4, 9]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_hung_startup_worker_falls_back_to_gcs(local_cluster):
    """r7 finding (a): a worker that hangs during startup (the NM's
    deferred lease reply never resolves) must not wedge that shape's
    pipeline — the caller bounds the local grant by the worker-start
    timeout and spills back to the GCS-brokered path."""
    nm = _nm()
    lm = _worker()._lease_mgr
    # Simulate the hang: checkout never replies (the spawned worker is
    # alive but never registers, so the deferred reply is parked forever).
    orig_checkout = nm._checkout_worker
    nm._checkout_worker = lambda *a, **k: None
    lm._worker_timeout = 1.0
    try:
        @ray_tpu.remote(num_cpus=2)
        def two_cpu():
            return "ok"

        t0 = time.time()
        # Before the fix this get wedges: the local-lease future never
        # resolves, the shape's queue never drains, no GCS fallback.
        assert ray_tpu.get(two_cpu.remote(), timeout=30) == "ok"
        assert time.time() - t0 < 30
    finally:
        nm._checkout_worker = orig_checkout
        lm._worker_timeout = float(config.worker_start_timeout_s) + 10.0


def test_nm_reaps_hung_startup_lease_worker(local_cluster):
    """NM-side bound for the same finding: a STARTING worker holding a
    deferred lease reply past worker_start_timeout_s is killed, which
    errors the deferred reply (caller falls back) and releases the
    grant's ledger hold via the normal death path."""
    import subprocess
    import sys as _sys

    from ray_tpu._private import node_manager as nm_mod
    from ray_tpu._private.ids import WorkerID

    nm = _nm()
    old_timeout = config.worker_start_timeout_s
    config.set("worker_start_timeout_s", 0.5)
    proc = subprocess.Popen([_sys.executable, "-c",
                             "import time; time.sleep(300)"])

    errored = []

    class _FakeConn:
        def reply_error(self, msg_id, err):
            errored.append(err)

    handle = nm_mod.WorkerHandle(
        worker_id=WorkerID.from_random().binary(), proc=proc)
    handle.lease_reply = (_FakeConn(), 0)   # deferred reply parked
    handle.busy_since = time.time()
    try:
        with nm._lock:
            nm._workers[handle.worker_id] = handle
        _wait_for(lambda: proc.poll() is not None, timeout=15,
                  msg="hung startup worker to be reaped")
        _wait_for(lambda: errored, timeout=15,
                  msg="deferred lease reply to be errored")
    finally:
        config.set("worker_start_timeout_s", old_timeout)
        try:
            proc.kill()
        except Exception:
            pass
        with nm._lock:
            nm._workers.pop(handle.worker_id, None)


def test_daemon_pool_concurrent_submit_spawns(local_cluster):
    """r7 finding (b): two back-to-back submits that both observe one
    idle thread must not BOTH skip the spawn — the idle check-and-reserve
    is atomic under the pool lock, so the second submit spawns and both
    fns run concurrently."""
    import threading

    from ray_tpu._private.worker import _DaemonPool

    pool = _DaemonPool(4, "test-pool")
    warm = threading.Event()
    pool.submit(warm.set)
    assert warm.wait(5)
    _wait_for(lambda: pool._idle == 1, timeout=5, msg="one idle thread")

    release = threading.Event()
    started = [threading.Event(), threading.Event()]

    def blocker(i):
        started[i].set()
        release.wait(30)

    # Back-to-back: with the racy accounting both submits see _idle == 1
    # and neither spawns — the second fn strands behind the first.
    pool.submit(lambda: blocker(0))
    pool.submit(lambda: blocker(1))
    try:
        assert started[0].wait(5), "first submit never ran"
        assert started[1].wait(5), \
            "second submit stranded: spawn/idle race lost a worker"
    finally:
        release.set()


def test_spawn_failure_keeps_local_capacity(local_cluster):
    """r7 finding (c): _on_create_actor/_on_lease_task must release their
    _local_avail mirror-subtract when _spawn_worker raises — repeated
    spawn failures must not permanently shrink local capacity."""
    from ray_tpu._private.ids import ActorID, JobID
    from ray_tpu._private.task_spec import ActorCreationSpec

    nm = _nm()
    baseline = dict(nm._local_avail.to_dict())

    def boom(*a, **k):
        raise OSError("spawn failed (injected)")

    orig_spawn = nm._spawn_worker
    nm._spawn_worker = boom
    try:
        for _ in range(3):
            spec = ActorCreationSpec(
                actor_id=ActorID.from_random(),
                job_id=JobID.from_random(),
                class_key="nonexistent", args=b"", arg_deps=[],
                resources={"CPU": 1.0},
                # env_vars force the fresh-spawn path (no pooled reuse).
                runtime_env={"env_vars": {"X": "1"}})
            nm._on_create_actor(spec)
        _wait_for(lambda: not nm._res_held_actors,
                  msg="actor holds released after spawn failure")
        assert nm._local_avail.to_dict() == baseline, \
            "spawn failures leaked local capacity"
    finally:
        nm._spawn_worker = orig_spawn


def test_local_scheduling_disabled_is_centralized(monkeypatch):
    """The A/B baseline: toggle off -> no local grants, every placement
    serializes through the GCS (classic path), tasks still complete."""
    monkeypatch.setenv("RAY_TPU_LOCAL_SCHEDULING_ENABLED", "0")
    config.set("local_scheduling_enabled", False)
    try:
        ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)

        @ray_tpu.remote
        def sq(x):
            return x * x

        assert ray_tpu.get([sq.remote(i) for i in range(10)]) == \
            [i * i for i in range(10)]
        assert _worker()._lease_mgr is None
        assert _nm().local_grants_total == 0
    finally:
        ray_tpu.shutdown()
        config.set("local_scheduling_enabled", True)
