"""Tune tests: grid/random search, best-result selection, ASHA early
stopping, trial failure retry."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import FailureConfig, RunConfig
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner
from ray_tpu.tune.search import BasicVariantGenerator


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_variant_generation():
    space = {"a": tune.grid_search([1, 2, 3]),
             "b": tune.grid_search(["x", "y"]),
             "c": 42,
             "d": tune.uniform(0.0, 1.0)}
    variants = BasicVariantGenerator(space, num_samples=2, seed=0).variants()
    assert len(variants) == 12  # 3 * 2 grid, x2 samples
    assert all(v["c"] == 42 for v in variants)
    assert all(0.0 <= v["d"] <= 1.0 for v in variants)
    assert {(v["a"], v["b"]) for v in variants} == {
        (a, b) for a in (1, 2, 3) for b in ("x", "y")}


def test_nested_and_domains():
    space = {"opt": {"lr": tune.loguniform(1e-4, 1e-1),
                     "wd": tune.choice([0.0, 0.1])},
             "n": tune.randint(1, 5)}
    vs = BasicVariantGenerator(space, num_samples=5, seed=1).variants()
    assert len(vs) == 5
    assert all(1e-4 <= v["opt"]["lr"] <= 1e-1 for v in vs)
    assert all(v["n"] in (1, 2, 3, 4) for v in vs)


def _objective(config):
    # Deterministic "training": loss shrinks faster for larger lr.
    loss = 10.0 / config["lr"]
    for i in range(3):
        tune.report({"loss": loss / (i + 1)})


def test_tuner_grid(ray_4cpu, tmp_path):
    tuner = Tuner(
        _objective,
        param_space={"lr": tune.grid_search([1.0, 2.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min",
                               max_concurrent_trials=2),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    best = grid.get_best_result()
    assert best.metrics["loss"] == pytest.approx(10.0 / 5.0 / 3)
    assert not grid.errors
    # training_iteration injected
    assert best.metrics["training_iteration"] == 3


def _asha_objective(config):
    import time
    for i in range(1, 10):
        tune.report({"score": config["quality"] * i,
                     "training_iteration": i})
        time.sleep(0.01)


def test_asha_stops_bad_trials(ray_4cpu, tmp_path):
    tuner = Tuner(
        _asha_objective,
        param_space={"quality": tune.grid_search([1.0, 10.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", max_concurrent_trials=2,
            scheduler=ASHAScheduler(metric="score", mode="max", max_t=9,
                                    grace_period=2, reduction_factor=2)),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    states = sorted(t.state for t in grid._trials)
    # the quality=1 trial should be stopped early at some rung
    assert "STOPPED" in states or all(s == "TERMINATED" for s in states)
    best = grid.get_best_result()
    assert best.metrics["score"] == 90.0


_RETRY_KEY = "tune_retry_marker"


def _flaky_objective(config):
    import os
    marker = config["marker"]
    if not os.path.exists(marker):
        open(marker, "w").write("x")
        raise RuntimeError("first attempt fails")
    tune.report({"loss": 1.0})


def test_trial_retry(ray_4cpu, tmp_path):
    tuner = Tuner(
        _flaky_objective,
        param_space={"marker": str(tmp_path / "m1")},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(
            name="flaky", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)),
    )
    grid = tuner.fit()
    assert not grid.errors
    assert grid.get_best_result().metrics["loss"] == 1.0


def test_pbt_exploits_toward_better_config(ray_4cpu, tmp_path):
    """PBT: bottom-quantile trials clone the leader's checkpoint and
    continue with a perturbed copy of its hyperparameters — the
    population's final scores must beat the worst initial lr's ceiling."""
    import time as _time

    from ray_tpu import train
    from ray_tpu.train import Checkpoint
    from ray_tpu.tune import PopulationBasedTraining, TuneConfig, Tuner

    def train_fn(config):
        ckpt = train.get_checkpoint()
        score = ckpt.to_dict()["score"] if ckpt else 0.0
        for _ in range(25):
            score += config["lr"]  # higher lr -> faster score growth
            train.report({"score": score},
                         checkpoint=Checkpoint.from_dict({"score": score}))
            _time.sleep(0.12)

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=3,
        hyperparam_mutations={"lr": lambda: 1.0}, quantile_fraction=0.34,
        seed=0)
    tuner = Tuner(
        train_fn,
        param_space={"lr": tune.grid_search([0.01, 0.02, 1.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt,
                               num_samples=1),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert pbt.num_exploits >= 1, "PBT never exploited"
    scores = sorted(r.metrics["score"] for r in grid)
    # The low-lr trials top out at 25*0.02 = 0.5 on their own; an
    # exploited trial clones the lr=1.0 leader's checkpoint + config, so
    # at least one laggard must end far above its solo ceiling.
    assert scores[1] > 1.0, scores


@pytest.fixture
def ray_8cpu_gang():
    ctx = ray_tpu.init(num_cpus=8, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_tune_trial_as_multiworker_gang(ray_8cpu_gang, tmp_path):
    """VERDICT r3 weak #6: a trial can be a multi-worker PG-backed
    trainer — Tune reserves the whole gang atomically via
    PlacementGroupFactory (bundle 0 = trial driver, 1..N = workers),
    and two such trials run without partial-placement deadlock."""
    import ray_tpu
    from ray_tpu import tune
    from ray_tpu.train import DataParallelTrainer, ScalingConfig, RunConfig

    def gang_loop(config):
        from ray_tpu import train
        ws = train.get_world_size()
        assert ws == 2
        # Prove the collective group spans the gang.
        total = float(train.session.allreduce(
            __import__("numpy").ones(1))[0])
        train.report({"world": ws, "lr": config["lr"], "sum": total})

    trainer = DataParallelTrainer(
        gang_loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path / "inner")),
        backend="store",
    )
    tuner = tune.Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": tune.grid_search([0.1, 0.2])}},
        tune_config=tune.TuneConfig(
            metric="sum", mode="max",
            resources_per_trial=tune.PlacementGroupFactory(
                [{"CPU": 1.0}, {"CPU": 1.0}, {"CPU": 1.0}]),
        ),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2 and not grid.errors
    for r in grid:
        assert r.metrics["world"] == 2
        assert r.metrics["sum"] == 2.0
    # All trial PGs removed: full capacity restored.
    avail = ray_tpu.available_resources()
    assert avail.get("CPU", 0) == 8.0, avail
