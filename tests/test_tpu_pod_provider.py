"""TPU-pod (queued-resources) autoscaling: slices as the scaling unit.

Reference analogs: NodeProvider plugin (autoscaler/node_provider.py:13),
batched reconcile (autoscaler/batching_node_provider.py), and the GCP
queued-resources state machine (WAITING_FOR_RESOURCES -> ACTIVE at slice
granularity). Verified TPU-first behaviors: 2-slice scale-up from gang
demand, slice-label injection feeding slice-affine PG placement,
capacity-gated FIFO granting, and slice-atomic teardown on idle.
"""

import time

import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod
from ray_tpu.autoscaler import (
    AutoscalerConfig, FakeTpuCloud, NodeType, StandardAutoscaler,
    TpuPodProvider,
)
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def head_only_cluster():
    cluster = Cluster(initialize_head=True, head_node_args={
        "num_cpus": 2, "num_tpus": 0})
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()


def test_two_slice_scale_up_and_slice_affine_pg(head_only_cluster):
    cluster = head_only_cluster
    cloud = FakeTpuCloud(cluster, capacity_slices=2)
    provider = TpuPodProvider(cloud)
    config = AutoscalerConfig(
        node_types=[NodeType("v5e_slice",
                             {"CPU": 4.0, "TPU": 8.0, "hosts": 2},
                             max_workers=4)],
        max_workers=4, idle_timeout_s=2.0)
    core = worker_mod.require_worker()
    scaler = StandardAutoscaler(core.gcs, provider, config)

    # Gang demand: a STRICT_SPREAD PG of 4 TPU bundles (2 slices' worth).
    from ray_tpu.util.placement_group import placement_group
    pg = placement_group([{"TPU": 4.0} for _ in range(4)],
                         strategy="SPREAD")

    summary = scaler.run_once()
    assert summary["launched"] >= 2, summary

    # The fake cloud grants both slices; their hosts register with
    # slice labels and the PG becomes placeable.
    assert pg.wait(timeout_seconds=60)
    nodes = ray_tpu.nodes()
    slices = {n["Labels"].get("slice") for n in nodes
              if n["Labels"].get("slice")}
    assert len(slices) == 2, slices
    assert ray_tpu.cluster_resources().get("TPU", 0) == 16.0

    # Release the gang reservation, then prove TPU tasks actually run
    # on the autoscaled slices (two tasks — each spawns a dedicated
    # worker with a fresh JAX import, slow on the 1-core CI box).
    from ray_tpu.util.placement_group import remove_placement_group
    remove_placement_group(pg)

    @ray_tpu.remote(num_tpus=1)
    def which_slice():
        import os
        return os.environ.get("TPU_VISIBLE_CHIPS", "?")

    out = ray_tpu.get([which_slice.remote() for _ in range(2)], timeout=240)
    assert len(out) == 2
    deadline = time.time() + 45
    while time.time() < deadline:
        scaler.run_once()
        if not provider.non_terminated_nodes():
            break
        time.sleep(0.5)
    assert not provider.non_terminated_nodes()
    assert ray_tpu.cluster_resources().get("TPU", 0) == 0.0


def test_capacity_gated_fifo_granting(head_only_cluster):
    """Requests beyond cloud capacity queue (WAITING_FOR_RESOURCES) and
    are granted FIFO as capacity frees — the queued-resources contract."""
    from ray_tpu.autoscaler.tpu_pod_provider import ACTIVE, QUEUED

    cluster = head_only_cluster
    cloud = FakeTpuCloud(cluster, capacity_slices=1)
    provider = TpuPodProvider(cloud)

    first = provider.create_node("v5e_slice",
                                 {"CPU": 2.0, "TPU": 4.0, "hosts": 1}, 1)[0]
    second = provider.create_node("v5e_slice",
                                  {"CPU": 2.0, "TPU": 4.0, "hosts": 1}, 1)[0]
    listing = cloud.list_queued_resources()
    assert listing[first]["state"] == ACTIVE
    assert listing[second]["state"] == QUEUED
    # Pending requests still count as non-terminated (no duplicate asks).
    assert set(provider.non_terminated_nodes()) == {first, second}

    provider.terminate_node(first)
    listing = cloud.list_queued_resources()
    assert listing[second]["state"] == ACTIVE   # FIFO grant on freed cap
    provider.terminate_node(second)
    assert provider.non_terminated_nodes() == []
