"""Serve-tier fault tolerance: crash-transparent migration of in-flight
requests with bit-identical resume.

Layers under test, bottom up:

- engine: a failed/stopped engine turns in-flight requests into durable
  resume descriptors (``EngineFailedError``), and ``submit(generated=)``
  continues a request bit-identically (per-request ``fold_in(seed,
  position)`` sampling keys), both KV layouts, greedy and sampled;
- handle/router: a replica death mid-stream re-opens the stream on a
  healthy replica from the tokens already DELIVERED client-side (never a
  duplicate, never a gap), via deterministic fault injection
  (``die:after_tokens``) and a real SIGKILL;
- unary calls migrate (retry-from-scratch is exact: nothing delivered);
- controller: rolling-restart ``drain`` — redeploys recycle every
  replica with zero failed in-flight requests; fault stats recorded;
- kv_transfer: a dead prefill replica's unresolvable handoff raises
  typed ``KVAdoptTimeoutError`` bounded by ``serve_kv_adopt_timeout_s``;
- plain (non-LLM) streams WITHOUT a resume rewriter keep today's
  fail-loud typed behavior under mid-stream SIGKILL.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu._private.config import config
from ray_tpu.exceptions import (
    EngineFailedError, KVAdoptTimeoutError, RayActorError,
    ReplicaDrainingError, WorkerCrashedError,
)
from ray_tpu.serve.llm import EngineConfig, build_llm_app
from ray_tpu.serve.llm.engine import InflightBatchEngine
from ray_tpu.serve.llm.replicas import _build_model

ENGINE_CONFIG = dict(
    preset="tiny", model_overrides={"dtype": "float32"},
    max_slots=4, max_len=64, prompt_buckets=(16,), max_new_tokens=16)

PROMPT = [5, 9, 2, 11, 3]
N = 10


@pytest.fixture(scope="module")
def serve_cluster():
    ctx = ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    serve.start(http_port=None)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def _make_engine(**overrides) -> InflightBatchEngine:
    ec = EngineConfig.from_dict(dict(ENGINE_CONFIG, **overrides))
    cfg, params = _build_model(ec)
    return InflightBatchEngine(params, cfg, ec)


def _controller():
    from ray_tpu.serve.controller import CONTROLLER_NAME

    return ray_tpu.get_actor(CONTROLLER_NAME)


def _replicas_of(name):
    return ray_tpu.get(_controller().get_replicas.remote(name),
                       timeout=30)


def _pids_of(name):
    out = {}
    for r in _replicas_of(name):
        s = ray_tpu.get(r.stats.remote(), timeout=30)
        out[s["pid"]] = s
    return out


# ---------------------------------------------------------------- engine


@pytest.mark.parametrize("paged", [False, True],
                         ids=["reserved", "paged"])
@pytest.mark.parametrize("sampling", [{}, {"temperature": 0.8, "top_k": 5}],
                         ids=["greedy", "sampled"])
def test_engine_resume_bit_identical(paged, sampling):
    """submit(generated=ref[:k]) continues exactly where an undisturbed
    run would be — the recompute-preemption invariant extended to
    cross-engine resume, both KV layouts, greedy AND sampled."""
    eng = _make_engine(paged_kv=paged, **sampling)
    try:
        ref = eng.generate(PROMPT, N, seed=3)
        assert len(ref) == N
        for k in (1, 4, N - 1):
            resumed = eng.generate(PROMPT, N, seed=3, generated=ref[:k])
            assert resumed == ref[k:], (k, resumed, ref)
    finally:
        eng.stop()


def test_engine_step_failure_poisons_with_resume_descriptor():
    """fault_inject="step_error:after=K": the failing step turns every
    in-flight request into an EngineFailedError CARRYING a resume
    descriptor; replaying the descriptor on a fresh engine completes
    the stream bit-identically; the failed engine still serves new
    requests (poison is per-request, not per-engine)."""
    ref_eng = _make_engine()
    try:
        ref = ref_eng.generate(PROMPT, N, seed=0)
    finally:
        ref_eng.stop()

    eng = _make_engine(fault_inject="step_error:after=4")
    try:
        rid = eng.submit(PROMPT, N, seed=0)
        got, err = [], None
        try:
            for chunk in eng.stream(rid):
                got.extend(chunk)
        except EngineFailedError as e:
            err = e
        assert err is not None, "fault injection never fired"
        assert err.reason == "step_failure"
        d = err.descriptor
        assert d["prompt"] == PROMPT and d["seed"] == 0
        assert d["max_tokens"] == N
        # The descriptor's generated prefix matches the reference run.
        assert d["generated"] == ref[:len(d["generated"])]
        # Delivered tokens are a prefix of generated: resuming from the
        # DELIVERED count never duplicates, never gaps.
        assert got == d["generated"][:len(got)]

        # The engine survived the poisoned step.
        assert eng.generate(PROMPT, 4, seed=0) == ref[:4]
    finally:
        eng.stop()

    resumed = _make_engine()
    try:
        out = resumed.generate(d["prompt"], d["max_tokens"], d["seed"],
                               generated=d["generated"])
        assert d["generated"] + out == ref
    finally:
        resumed.stop()


def test_engine_stop_and_dump_inflight_descriptors():
    """engine.stop() with requests in flight errors them with
    reason="engine_stopped" resume descriptors (not a bare
    RuntimeError); dump_inflight() exposes the same descriptors for
    drain-time handoff."""
    eng = _make_engine()
    rid = eng.submit(PROMPT, N, seed=1)
    # Let a few tokens land so the descriptor is mid-flight, not empty.
    deadline = time.time() + 30
    got = []
    while time.time() < deadline and len(got) < 2:
        got.extend(eng.drain(rid, max_wait_s=0.5)["tokens"])
    assert got, "engine produced nothing"
    dump = eng.dump_inflight()
    assert len(dump) == 1
    assert dump[0]["prompt"] == PROMPT
    assert dump[0]["generated"][:len(got)] == got
    eng.stop()
    with pytest.raises(EngineFailedError) as ei:
        eng.drain(rid, max_wait_s=0.5)
    assert ei.value.reason == "engine_stopped"
    assert ei.value.descriptor["prompt"] == PROMPT


def test_fault_inject_config_fallback():
    """The ``serve_fault_inject`` config knob arms engines that were
    built WITHOUT an explicit EngineConfig.fault_inject (same-process
    fallback for tests and the chaos bench)."""
    config.set("serve_fault_inject", "step_error:after=2")
    try:
        eng = _make_engine()
    finally:
        config.set("serve_fault_inject", "")
    try:
        with pytest.raises(EngineFailedError):
            eng.generate(PROMPT, N, seed=0)
    finally:
        eng.stop()

    with pytest.raises(ValueError, match="unknown serve_fault_inject"):
        _make_engine(fault_inject="explode:after=1")


# ------------------------------------------------- streams under crashes


@pytest.mark.parametrize("sampling", [{}, {"temperature": 0.8, "top_k": 5}],
                         ids=["greedy", "sampled"])
def test_stream_survives_engine_replica_death(serve_cluster, sampling):
    """die:after_tokens SIGKILLs the engine replica mid-stream; the
    router migrates the stream to the surviving replica and the client
    sees the exact undisturbed token sequence — greedy and sampled."""
    ref_eng = _make_engine(**sampling)
    try:
        ref = ref_eng.generate(PROMPT, N, seed=5)
    finally:
        ref_eng.stop()

    name = "llmdie" + ("s" if sampling else "g")
    handle = serve.run(
        build_llm_app(dict(ENGINE_CONFIG, fault_inject="die:after_tokens=8",
                           **sampling),
                      mode="combined", name=name, num_replicas=2),
        route_prefix=f"/{name}")
    try:
        chunks = list(handle.generate_stream.remote_gen(
            {"prompt": PROMPT, "n": N, "seed": 5}))
        flat = [t for c in chunks for t in c]
        assert flat == ref, (flat, ref)
        # The stream migrated inside the router replica; its tally is
        # surfaced through the replica stats RPC.
        migrations = sum(
            s.get("request_migrations_total", 0)
            for s in _pids_of(name).values())
        assert migrations >= 1
        # The controller detected the death and recorded the restart.
        fs = ray_tpu.get(_controller().fault_stats.remote(), timeout=30)
        assert fs["replica_restarts_total"] >= 1
    finally:
        serve.delete(name)
        serve.delete(f"{name}-engine")


def test_stream_survives_real_sigkill(serve_cluster):
    """No fault injection: a real mid-stream SIGKILL of the serving
    engine replica, with the stream opened straight against the pool
    handle (migration happens in THIS process) — output bit-identical,
    migration counted locally."""
    from ray_tpu.serve.handle import DeploymentHandle
    from ray_tpu.serve.migration import llm_stream_resume, migration_stats

    ref_eng = _make_engine()
    try:
        ref = ref_eng.generate(PROMPT, N, seed=0)
    finally:
        ref_eng.stop()

    serve.run(build_llm_app(ENGINE_CONFIG, mode="combined",
                            name="llmkill", num_replicas=2),
              route_prefix="/llmkill")
    try:
        pool = DeploymentHandle("llmkill-engine", "generate_stream")
        req = {"prompt": PROMPT, "n": N, "seed": 0}
        before = migration_stats()["request_migrations_total"]
        gen = pool.remote_gen(req, _resume=llm_stream_resume(req))
        # Kill the serving replica BEFORE the first pull: nothing is
        # delivered yet, so the client-side tally forces a clean resume
        # (and the batched first pull of a fast tiny model can't race
        # the whole stream past the kill).
        pid = ray_tpu.get(gen._replica.stats.remote(), timeout=30)["pid"]
        os.kill(pid, signal.SIGKILL)
        got = [list(chunk) for chunk in gen]
        flat = [t for c in got for t in c]
        assert flat == ref, (flat, ref)
        after = migration_stats()["request_migrations_total"]
        assert after >= before + 1
    finally:
        serve.delete("llmkill")
        serve.delete("llmkill-engine")


def test_disaggregated_stream_survives_decode_death(serve_cluster):
    """Disaggregated mode: SIGKILL the decode replica serving the
    stream; the router's resume rewriter re-prefills prompt + delivered
    locally on the surviving decode replica (resume_stream) and the
    client stream completes bit-identically."""
    ref_eng = _make_engine()
    try:
        ref = ref_eng.generate(PROMPT, N, seed=0)
    finally:
        ref_eng.stop()

    handle = serve.run(
        build_llm_app(ENGINE_CONFIG, mode="disaggregated", name="llmdis",
                      num_decode_replicas=2),
        route_prefix="/llmdis")
    try:
        gen = handle.generate_stream.remote_gen(
            {"prompt": PROMPT, "n": N, "seed": 0})
        got = [list(next(gen))]           # the prefill (TTFT) token
        # Find the decode replica with the live stream and kill it.
        busy = [s["pid"] for s in _pids_of("llmdis-decode").values()
                if s.get("ongoing", 0) > 0]
        assert busy, "no decode replica holds the stream"
        for pid in busy:
            os.kill(pid, signal.SIGKILL)
        for chunk in gen:
            got.append(list(chunk))
        flat = [t for c in got for t in c]
        assert flat == ref, (flat, ref)
    finally:
        serve.delete("llmdis")
        serve.delete("llmdis-prefill")
        serve.delete("llmdis-decode")


def test_kv_adopt_timeout_typed(serve_cluster):
    """adopt_kv on refs whose producer is gone raises typed
    KVAdoptTimeoutError bounded by serve_kv_adopt_timeout_s — not a
    60s-hardcoded wedge of the decode admission path."""
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.worker import ObjectRef
    from ray_tpu.serve.llm.kv_transfer import adopt_kv

    ghost = ObjectRef(ObjectID.from_random())
    config.set("serve_kv_adopt_timeout_s", 0.5)
    try:
        t0 = time.monotonic()
        with pytest.raises(KVAdoptTimeoutError) as ei:
            adopt_kv({"k_ref": ghost, "v_ref": ghost,
                      "length": 5, "first_token": 1})
        assert time.monotonic() - t0 < 30
        assert ei.value.timeout_s == 0.5
    finally:
        config.set("serve_kv_adopt_timeout_s", 60.0)


# --------------------------------------------- plain deployments + drain


@serve.deployment(num_replicas=2, name="ft-unary")
class _SlowEcho:
    def __call__(self, x):
        time.sleep(1.0)
        return x


def test_unary_migration_on_replica_death(serve_cluster):
    """A unary call in flight on a SIGKILLed replica is resubmitted to
    the survivor (retry-from-scratch is exact: nothing was delivered)
    and counted as a migration."""
    from ray_tpu.serve.migration import migration_stats

    handle = serve.run(_SlowEcho.bind(), http_port=None)
    try:
        # Warm both replicas so stats expose pids.
        handle.remote("warm").result(timeout=60)
        config.set("serve_request_max_migrations", 10)
        before = migration_stats()["request_migrations_total"]
        resp = handle.remote("payload")
        time.sleep(0.3)
        busy = [s["pid"] for s in _pids_of("ft-unary").values()
                if s.get("ongoing", 0) > 0]
        assert busy, "no replica reports the in-flight request"
        for pid in busy:
            os.kill(pid, signal.SIGKILL)
        assert resp.result(timeout=120) == "payload"
        assert migration_stats()["request_migrations_total"] >= before + 1
    finally:
        config.set("serve_request_max_migrations", 3)
        serve.delete("ft-unary")


@serve.deployment(num_replicas=1, name="ft-plainstream")
class _Ticker:
    def ticks(self, n):
        for i in range(int(n)):
            time.sleep(0.2)
            yield i


def test_plain_stream_sigkill_raises_typed(serve_cluster):
    """A generic (non-LLM) stream has no resume rewriter: a mid-stream
    replica SIGKILL surfaces typed actor-death errors — fail-loud, not
    a wedge, and not silent truncation."""
    handle = serve.run(_Ticker.bind(), http_port=None)
    try:
        gen = handle.ticks.remote_gen(50)
        assert next(gen) == 0
        pid = ray_tpu.get(gen._replica.stats.remote(), timeout=30)["pid"]
        os.kill(pid, signal.SIGKILL)
        with pytest.raises((RayActorError, WorkerCrashedError)):
            for _ in gen:
                pass
    finally:
        serve.delete("ft-plainstream")


def test_drained_replica_sheds_typed_and_stats(serve_cluster):
    """A draining replica refuses NEW work with ReplicaDrainingError
    (typed — the handle re-picks on it) while reporting draining=True;
    drain() returns once in-flight work finishes."""
    @serve.deployment(num_replicas=1, name="ft-drain")
    class Echo:
        def __call__(self, x):
            return x

    serve.run(Echo.bind(), http_port=None)
    try:
        (replica,) = _replicas_of("ft-drain")
        out = ray_tpu.get(replica.drain.remote(1.0), timeout=30)
        assert out["drained"] is True and out["ongoing"] == 0
        assert ray_tpu.get(replica.stats.remote(),
                           timeout=30)["draining"] is True
        with pytest.raises(ReplicaDrainingError):
            ray_tpu.get(replica.handle_request.remote(
                "__call__", ("x",), {}), timeout=30)
    finally:
        serve.delete("ft-drain")


def test_redeploy_drains_zero_failed_inflight(serve_cluster):
    """A redeploy (serve.run on an existing name) recycles every
    replica through the drain path: requests in flight on the old
    generation all complete, new traffic lands on the new generation,
    and the controller records the drain durations."""
    @serve.deployment(num_replicas=2, name="ft-redeploy")
    class Gen1:
        def __call__(self, x):
            time.sleep(0.5)
            return ("g1", x)

    @serve.deployment(num_replicas=2, name="ft-redeploy")
    class Gen2:
        def __call__(self, x):
            return ("g2", x)

    handle = serve.run(Gen1.bind(), http_port=None)
    try:
        handle.remote(0).result(timeout=60)
        fs0 = ray_tpu.get(_controller().fault_stats.remote(), timeout=30)
        results, errors = [], []

        def issue(i):
            try:
                results.append(handle.remote(i).result(timeout=120))
            except BaseException as e:  # pragma: no cover - fail below
                errors.append(e)

        threads = [threading.Thread(target=issue, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.15)          # requests in flight on gen 1
        serve.run(Gen2.bind(), http_port=None)
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 8
        # New traffic reaches generation 2.
        deadline = time.time() + 60
        while time.time() < deadline:
            if handle.remote("x").result(timeout=60) == ("g2", "x"):
                break
            time.sleep(0.2)
        else:
            pytest.fail("redeploy never switched traffic to gen 2")
        # Both old replicas went through the drain path.
        deadline = time.time() + 60
        while time.time() < deadline:
            fs = ray_tpu.get(_controller().fault_stats.remote(),
                             timeout=30)
            if len(fs["drain_duration_s"]) >= \
                    len(fs0["drain_duration_s"]) + 2:
                break
            time.sleep(0.2)
        assert len(fs["drain_duration_s"]) >= \
            len(fs0["drain_duration_s"]) + 2, fs
    finally:
        serve.delete("ft-redeploy")
