"""Device arrays (jax.Array) as first-class store objects
(``_private/device_objects.py``).

The bounded-copy contract under test (ISSUE 3 acceptance):

* put/get round-trip preserves dtype/shape/values — including extended
  ML dtypes (bfloat16) that numpy's ``dtype.str`` cannot spell;
* put performs NO host materialization beyond the arena slab on CPU
  backends (asserted via the staging-allocation probe counters) and the
  staged bytes land on the arena-wide accounting counter;
* cross-process-style get performs exactly ONE arena-backed
  ``device_put`` rebuild, and the arena pin (store refcount) holds until
  the rebuilt array is collected — surviving eviction pressure;
* same-process get returns the IDENTICAL array object, zero copies;
* ``_donate_result`` releases the producer's device buffer the moment
  staging completes;
* everything runs under ``JAX_PLATFORMS=cpu`` (conftest forces it), and
  the legacy pickle-via-host path still works with the feature off.
"""

import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import device_objects, serialization
from ray_tpu._private.config import config
from ray_tpu._private import worker as worker_mod
from ray_tpu.object_store import plasma


def _oid(i: int) -> bytes:
    return b"DV" + i.to_bytes(4, "little") + b"\x00" * 22


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "arena")
    plasma.create_store(path, capacity=64 * 1024 * 1024, max_objects=1024)
    client = plasma.PlasmaClient(path)
    yield client
    client.close()


@pytest.fixture
def ray_1cpu():
    # num_cpus=1 => a single worker process, so back-to-back same-shape
    # tasks land on the same leased worker (donation test needs that).
    ctx = ray_tpu.init(num_cpus=1, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _device_array(n_bytes: int, dtype=jnp.float32):
    n = n_bytes // np.dtype(dtype).itemsize
    arr = jnp.arange(n, dtype=dtype)
    return jax.block_until_ready(arr)


# ------------------------------------------------------------- round trip

@pytest.mark.parametrize("dtype", ["float32", "int8", "bfloat16"])
def test_roundtrip_preserves_dtype_shape_values(store, dtype):
    arr = jax.block_until_ready(
        jnp.arange(4096, dtype=dtype).reshape(64, 64))
    store.put_value(_oid(1), arr)
    back, ok = store.get_value(_oid(1), timeout_ms=0)
    assert ok
    assert isinstance(back, jax.Array)
    assert back.dtype == arr.dtype
    assert back.shape == arr.shape
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


def test_roundtrip_nested_in_pytree(store):
    arr = _device_array(2 << 20)
    value = {"weights": arr, "step": 7, "tag": "ckpt"}
    store.put_value(_oid(2), value)
    back, ok = store.get_value(_oid(2), timeout_ms=0)
    assert ok and back["step"] == 7 and back["tag"] == "ckpt"
    np.testing.assert_array_equal(np.asarray(back["weights"]),
                                  np.asarray(arr))


def test_frame_is_oob_not_inband(store):
    # The tensor must ride the out-of-band buffer channel, not the pickle
    # stream (default jax pickling embeds it in-band — the whole point of
    # the reducer is to avoid that copy).
    arr = _device_array(4 << 20)
    sobj = serialization.serialize(arr)
    assert len(sobj.metadata) < 64 * 1024
    assert sum(b.nbytes for b in sobj.buffers) >= arr.nbytes
    assert sobj.device_bytes == arr.nbytes


# ------------------------------------------------- copy-count contract

def test_put_no_host_materialization_and_staging_accounted(store):
    arr = _device_array(8 << 20)
    device_objects.reset_stats()
    staged_before = store.stats_ex()["device_staged_bytes"]
    store.put_value(_oid(3), arr)
    s = device_objects.stats()
    assert s["puts"] == 1
    # CPU backend: the host view aliases the device buffer, so the ONLY
    # copy is the write into the arena slab.
    assert s["host_materializations"] == 0
    assert s["staged_bytes"] == arr.nbytes
    assert store.stats_ex()["device_staged_bytes"] - staged_before == arr.nbytes


def test_get_exactly_one_rebuild_and_pin_lifecycle(store):
    arr = _device_array(8 << 20)  # > zero_copy_min => arena-backed view
    store.put_value(_oid(4), arr)
    device_objects.reset_stats()
    back, ok = store.get_value(_oid(4), timeout_ms=0)
    assert ok
    assert device_objects.stats()["rebuilds"] == 1
    # The store slot is pinned while the rebuilt array lives (eviction-
    # exempt), and released once it is collected.
    st = store.stats_ex()
    assert st["pinned_objects"] >= 1 and st["pinned_bytes"] >= arr.nbytes
    del back
    gc.collect()
    st = store.stats_ex()
    assert st["pinned_objects"] == 0 and st["pinned_bytes"] == 0


def test_pin_survives_eviction_pressure(store):
    arr = _device_array(8 << 20)
    store.put_value(_oid(5), arr)
    back, ok = store.get_value(_oid(5), timeout_ms=0)
    assert ok
    expect = np.asarray(arr).copy()
    # Hammer the 64 MiB arena with ~80 MiB of churn: everything unpinned
    # gets LRU-evicted, the pinned device object must not.
    for i in range(80):
        store.put_value(_oid(100 + i), np.ones(1 << 20, np.uint8))
    assert store.stats()["evictions"] > 0
    assert store.contains(_oid(5))
    np.testing.assert_array_equal(np.asarray(back), expect)
    del back
    gc.collect()
    # Consumer dropped the array: the slot is reclaimable again.
    for i in range(80):
        store.put_value(_oid(300 + i), np.ones(1 << 20, np.uint8))
    assert not store.contains(_oid(5))


# ------------------------------------------------- same-process handoff

def test_same_process_get_returns_identical_object(ray_1cpu):
    w = worker_mod.global_worker()
    arr = _device_array(4 << 20)
    device_objects.reset_stats()
    ref = ray_tpu.put(arr)
    assert ray_tpu.get(ref) is arr
    assert ray_tpu.get(ref) is arr
    s = device_objects.stats()
    assert s["local_hits"] == 2 and s["rebuilds"] == 0
    # Clearing the registry simulates a different consumer process: the
    # arena rebuild path kicks in, exactly once per get.
    w._device_local.clear()
    back = ray_tpu.get(ref)
    assert back is not arr
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
    assert device_objects.stats()["rebuilds"] == 1


def test_task_chain_stays_by_reference(ray_1cpu):
    # An actor/worker chaining steps: the consumer task resolves its arg
    # from the producer's weak registry when both run in one process.
    @ray_tpu.remote
    def make():
        a = jnp.ones((256, 256), jnp.float32)
        return jax.block_until_ready(a)

    @ray_tpu.remote
    def consume(x):
        assert isinstance(x, jax.Array)
        return float(x.sum())

    r = make.remote()
    assert ray_tpu.get(consume.remote(r)) == 256.0 * 256.0


# ------------------------------------------------------------ donation

def test_donation_releases_producer_buffer_unit():
    class _Core:
        pass

    core = _Core()
    core._device_local = {}
    arr = _device_array(1 << 20)
    device_objects.note_return(core, b"d" * 28, arr, donate=True)
    assert arr.is_deleted()
    assert core._device_local == {}  # donated arrays are not registered

    arr2 = _device_array(1 << 20)
    device_objects.note_return(core, b"e" * 28, arr2, donate=False)
    assert not arr2.is_deleted()
    assert core._device_local[b"e" * 28] is arr2


def test_donate_result_flag_plumbs_to_task_spec():
    from ray_tpu.remote_function import RemoteFunction

    rf = RemoteFunction(lambda: None, {"_donate_result": True})
    assert rf._options["_donate_result"] is True
    from ray_tpu._private.task_spec import TaskSpec

    assert TaskSpec.__dataclass_fields__["donate_result"].default is False


def test_donation_multi_return_same_array(ray_1cpu):
    # num_returns=2 returning (x, x): donation must be deferred until
    # BOTH slots are staged — deleting at slot 0 would make slot 1
    # serialize a dead buffer and fail the task after user code ran.
    @ray_tpu.remote(num_returns=2, _donate_result=True)
    def twice():
        x = jax.block_until_ready(jnp.full(64, 5.0, jnp.float32))
        return x, x

    r1, r2 = twice.remote()
    a, b = ray_tpu.get([r1, r2])
    np.testing.assert_array_equal(np.asarray(a), np.full(64, 5.0,
                                                         np.float32))
    np.testing.assert_array_equal(np.asarray(b), np.asarray(a))


def test_lookup_local_respects_toggle(ray_1cpu):
    # The by-reference short-circuit must stand down with the feature
    # off, or the A/B off-baseline is contaminated by on-path hits.
    w = worker_mod.global_worker()
    arr = _device_array(2 << 20)
    ref = ray_tpu.put(arr)
    assert ray_tpu.get(ref) is arr
    config.set("device_objects_enabled", False)
    try:
        assert ray_tpu.get(ref) is not arr
    finally:
        config.set("device_objects_enabled", True)
    assert ray_tpu.get(ref) is arr


def test_donation_end_to_end(ray_1cpu):
    # Producer task stages its return, donation deletes its HBM buffer;
    # a follow-up task in the same worker process observes the deletion.
    @ray_tpu.remote(_donate_result=True)
    def produce():
        import builtins

        a = jax.block_until_ready(jnp.ones((128, 128), jnp.float32))
        builtins._rtpu_donated_probe = a
        return a

    @ray_tpu.remote
    def check():
        import builtins

        a = getattr(builtins, "_rtpu_donated_probe", None)
        return None if a is None else a.is_deleted()

    out = ray_tpu.get(produce.remote())
    np.testing.assert_array_equal(np.asarray(out),
                                  np.ones((128, 128), np.float32))
    deleted = ray_tpu.get(check.remote())
    if deleted is None:
        pytest.skip("follow-up task landed on a different worker process")
    assert deleted is True


# ------------------------------------------------------- CPU fallback / off

def test_off_path_roundtrip(store):
    # With the feature off the reducer stands down: device arrays take
    # the legacy pickle-via-host path and still round-trip correctly.
    config.set("device_objects_enabled", False)
    try:
        arr = _device_array(2 << 20)
        device_objects.reset_stats()
        store.put_value(_oid(7), arr)
        assert device_objects.stats()["puts"] == 0
        back, ok = store.get_value(_oid(7), timeout_ms=0)
        assert ok
        np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))
    finally:
        config.set("device_objects_enabled", True)


def test_rebuild_numpy_fallback_matches():
    # The rebuild callable's jax-less branch: a consumer that cannot
    # device_put still gets a correct (read-only) numpy view.
    arr = _device_array(1 << 20)
    sobj = serialization.serialize(arr)
    data = sobj.to_bytes()
    back = serialization.loads_oob(data)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))


# ------------------------------------------------------- zero_copy_min knob

def test_zero_copy_min_env_override(monkeypatch):
    from ray_tpu._private.config import Config

    monkeypatch.setenv("RAY_TPU_ZERO_COPY_MIN", "4096")
    c = Config()
    c.define("zero_copy_min", 1 << 20, "doc")
    assert c.get("zero_copy_min") == 4096


def test_zero_copy_min_gates_pinning(store):
    arr = np.arange(1 << 16, dtype=np.float64)  # 512 KiB numpy object
    store.put_value(_oid(8), arr)
    old = config.zero_copy_min
    try:
        # Above the threshold: copied out, slot NOT pinned after get.
        config.set("zero_copy_min", 8 << 20)
        back, _ = store.get_value(_oid(8), timeout_ms=0)
        assert store.stats_ex()["pinned_objects"] == 0
        del back
        # Below the threshold: zero-copy view, slot pinned until GC.
        config.set("zero_copy_min", 1024)
        back, _ = store.get_value(_oid(8), timeout_ms=0)
        assert store.stats_ex()["pinned_objects"] == 1
        del back
        gc.collect()
        assert store.stats_ex()["pinned_objects"] == 0
    finally:
        config.set("zero_copy_min", old)


def test_stats_expose_pin_and_staging_keys(store):
    st = store.stats_ex()
    for key in ("pinned_objects", "pinned_bytes", "device_staged_bytes"):
        assert key in st and st[key] == 0
