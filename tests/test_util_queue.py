"""Actor-backed distributed Queue (reference: ray.util.queue.Queue)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util.queue import Empty, Full, Queue


@pytest.fixture
def ray_2cpu():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_fifo_roundtrip(ray_2cpu):
    q = Queue()
    for i in range(5):
        q.put(i)
    assert q.qsize() == 5
    assert [q.get(timeout=10) for _ in range(5)] == list(range(5))
    assert q.empty()
    with pytest.raises(Empty):
        q.get_nowait()


def test_maxsize_blocks_and_full(ray_2cpu):
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    assert q.full()
    with pytest.raises(Full):
        q.put_nowait(3)
    with pytest.raises(Full):
        q.put(3, timeout=0.3)

    def drain_later():
        time.sleep(0.5)
        q.get(timeout=10)

    t = threading.Thread(target=drain_later)
    t.start()
    q.put(3, timeout=10)  # unblocks once the drainer makes room
    t.join()
    assert q.qsize() == 2


def test_queue_across_tasks(ray_2cpu):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i * 10)
        return True

    @ray_tpu.remote
    def consumer(q, n):
        return [q.get(timeout=30) for _ in range(n)]

    p = producer.remote(q, 4)
    out = ray_tpu.get(consumer.remote(q, 4), timeout=60)
    assert ray_tpu.get(p, timeout=30)
    assert out == [0, 10, 20, 30]


def test_batch_ops(ray_2cpu):
    q = Queue()
    q.put_nowait_batch([1, 2, 3, 4])
    assert q.get_nowait_batch(3) == [1, 2, 3]
    with pytest.raises(Empty):
        q.get_nowait_batch(5)
