"""HTTP/SSE ingress tier tests: token streaming end-to-end through the
proxy (SSE wire format), client-disconnect cancellation freeing the
engine slot + KV blocks, watermark shedding with 429 + Retry-After,
downstream (engine-queue) backpressure mapping, and per-tenant
fairness."""

import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.llm import build_llm_app

HTTP_PORT = 18543

# Small paged engine: the ingress tests double as ingress+paged-KV
# integration coverage. max_seq is raised so a cancelled long request
# demonstrably frees its blocks mid-flight.
ENGINE_CONFIG = dict(
    preset="tiny",
    model_overrides={"dtype": "float32", "max_seq": 2048},
    max_slots=4, max_len=2048, prompt_buckets=(16,),
    max_new_tokens=2000, max_queue=8,
    paged_kv=True, kv_block_size=16, prefill_chunk=16)

PROMPT = [5, 9, 2, 11, 3]
N = 8


@pytest.fixture(scope="module")
def ingress_cluster():
    ctx = ray_tpu.init(
        num_cpus=6, object_store_memory=256 * 1024 * 1024,
        _system_config={
            "serve_ingress_max_inflight": 4,
            "serve_ingress_queue_watermark": 6,
            "serve_ingress_queue_timeout_s": 5.0,
        })
    serve.start(http_port=HTTP_PORT)
    handle = serve.run(build_llm_app(ENGINE_CONFIG, mode="combined",
                                     name="llm"),
                       route_prefix="/llm")
    # Warm the engine (compile) before any HTTP deadline applies.
    handle.remote({"prompt": PROMPT, "n": 4}).result(timeout=600)
    port = _proxy_port()
    yield ctx, port
    serve.shutdown()
    ray_tpu.shutdown()


def _proxy_port():
    from ray_tpu.serve.api import _controller

    deadline = time.time() + 30
    while time.time() < deadline:
        ports = ray_tpu.get(
            _controller().proxy_addresses.remote(), timeout=10)
        if ports:
            return next(iter(ports.values()))
        time.sleep(0.3)
    raise AssertionError("ingress proxy never came up")


def _post(port, path, body, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers=dict({"Content-Type": "application/json"},
                     **(headers or {})))
    return urllib.request.urlopen(req, timeout=timeout)


def _ref_tokens(n=N):
    from ray_tpu.serve.llm import EngineConfig
    from ray_tpu.serve.llm.replicas import _build_model
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.generate import generate

    cfg, params = _build_model(EngineConfig.from_dict(ENGINE_CONFIG))
    return [int(x) for x in generate(
        params, jnp.asarray([PROMPT], jnp.int32), jax.random.key(0),
        cfg=cfg, max_new_tokens=n, temperature=0.0)[0]]


def _engine_replica():
    from ray_tpu.serve.api import _controller

    reps = ray_tpu.get(
        _controller().get_replicas.remote("llm-engine"), timeout=10)
    assert reps
    return reps[0]


def _engine_stats():
    return ray_tpu.get(_engine_replica().stats.remote(), timeout=10)


def _read_sse(resp, deadline_s=120):
    """Parse one SSE stream: yields decoded ``data:`` payload strings."""
    deadline = time.time() + deadline_s
    buf = b""
    while time.time() < deadline:
        chunk = resp.read1(65536) if hasattr(resp, "read1") \
            else resp.read(1)
        if not chunk:
            return
        buf += chunk
        while b"\n\n" in buf:
            frame, buf = buf.split(b"\n\n", 1)
            for line in frame.split(b"\n"):
                if line.startswith(b"data: "):
                    yield line[len(b"data: "):].decode()


def test_completions_non_streaming(ingress_cluster):
    _, port = ingress_cluster
    with _post(port, "/v1/completions",
               {"model": "llm", "prompt": PROMPT, "max_tokens": N,
                "seed": 0}) as resp:
        assert resp.status == 200
        out = json.loads(resp.read())
    assert out["object"] == "text_completion"
    assert out["choices"][0]["tokens"] == _ref_tokens()
    assert out["usage"]["completion_tokens"] == N


def test_completions_missing_prompt_400(ingress_cluster):
    _, port = ingress_cluster
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(port, "/v1/completions", {"model": "llm", "max_tokens": 2})
    assert ei.value.code == 400


def test_sse_streaming_end_to_end(ingress_cluster):
    """Tokens flow through the proxy INCREMENTALLY as SSE data frames,
    terminated by [DONE], and reproduce the engine's exact tokens."""
    _, port = ingress_cluster
    resp = _post(port, "/v1/completions",
                 {"model": "llm", "prompt": PROMPT, "max_tokens": N,
                  "seed": 0, "stream": True}, timeout=120)
    assert resp.status == 200
    assert resp.headers["Content-Type"].startswith("text/event-stream")
    frames, done = [], False
    for payload in _read_sse(resp):
        if payload == "[DONE]":
            done = True
            break
        frames.append(json.loads(payload))
    resp.close()
    assert done, "stream never terminated with [DONE]"
    assert len(frames) >= 2, "tokens arrived as one blob, not a stream"
    tokens = [t for f in frames for t in f["choices"][0]["tokens"]]
    assert tokens == _ref_tokens()


def test_sse_client_disconnect_frees_slot_and_blocks(ingress_cluster):
    """Dropping the SSE connection mid-stream cancels the engine
    request: its slot and KV blocks free LONG before the 2000-token
    budget could finish (~9s on this box), and the engine goes idle."""
    _, port = ingress_cluster
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    body = json.dumps({"model": "llm", "prompt": PROMPT,
                       "max_tokens": 2000, "stream": True})
    conn.request("POST", "/v1/completions", body=body,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    # Read until the first data frame proves the request is in flight.
    got = b""
    while b"\n\n" not in got:
        got += resp.read1(4096)
    assert b"data: " in got
    st = _engine_stats()
    assert st["busy_slots"] >= 1 and st["kv_blocks_used"] > 0, st
    t_disconnect = time.monotonic()
    conn.sock.close()        # hard disconnect, no clean shutdown
    conn.close()

    deadline = time.monotonic() + 8
    freed = None
    while time.monotonic() < deadline:
        st = _engine_stats()
        if st["busy_slots"] == 0 and st["kv_blocks_used"] == 0 and \
                st["queue_depth"] == 0:
            freed = time.monotonic()
            break
        time.sleep(0.1)
    assert freed is not None, f"engine never freed the request: {st}"
    # Freed promptly — far sooner than the budget would complete.
    assert freed - t_disconnect < 6.0
    # And it stays idle: no zombie decode marching on.
    s1 = _engine_stats()["steps"]
    time.sleep(0.7)
    assert _engine_stats()["steps"] == s1


def test_watermark_shed_429_with_retry_after(ingress_cluster):
    """Arrivals beyond inflight budget + waiting-room watermark are
    shed with 429 + Retry-After while in-budget requests succeed, and
    the engine queue never exceeds max_queue."""
    _, port = ingress_cluster
    n_req = 14
    codes, retry_after = [], []
    lock = threading.Lock()
    max_queue_seen = [0]
    stop = threading.Event()

    def watch_queue():
        while not stop.is_set():
            try:
                q = _engine_stats()["queue_depth"]
                with lock:
                    max_queue_seen[0] = max(max_queue_seen[0], q)
            except Exception:
                pass
            time.sleep(0.05)

    def one(i):
        try:
            with _post(port, "/v1/completions",
                       {"model": "llm", "prompt": [1 + i, 2, 3],
                        "max_tokens": 64}, timeout=120) as resp:
                with lock:
                    codes.append(resp.status)
        except urllib.error.HTTPError as e:
            with lock:
                codes.append(e.code)
                if e.code == 429:
                    retry_after.append(e.headers.get("Retry-After"))

    watcher = threading.Thread(target=watch_queue, daemon=True)
    watcher.start()
    threads = [threading.Thread(target=one, args=(i,))
               for i in range(n_req)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    stop.set()
    watcher.join(timeout=5)

    assert codes.count(200) >= 1, codes
    shed = [c for c in codes if c in (429, 503)]
    assert shed, f"nothing shed under {n_req} concurrent requests: " \
                 f"{codes}"
    assert all(c in (200, 429, 503) for c in codes), codes  # no 500s
    assert any(r is not None for r in retry_after) or not any(
        c == 429 for c in codes)
    assert max_queue_seen[0] <= ENGINE_CONFIG["max_queue"]


def test_tenant_header_isolation(ingress_cluster):
    """Tenant tags ride the header end-to-end: a flood from one tenant
    does not starve another (DRR queue service), and per-tenant
    latency series are recorded by the proxy."""
    _, port = ingress_cluster
    results = {"a": [], "b": []}
    lock = threading.Lock()

    def req(tenant, i, n=32):
        try:
            with _post(port, "/v1/completions",
                       {"model": "llm", "prompt": [1 + i, 4, 7],
                        "max_tokens": n},
                       headers={"x-tenant": tenant},
                       timeout=120) as resp:
                with lock:
                    results[tenant].append(resp.status)
        except urllib.error.HTTPError as e:
            with lock:
                results[tenant].append(e.code)

    flood = [threading.Thread(target=req, args=("a", i))
             for i in range(8)]
    for t in flood:
        t.start()
    time.sleep(0.1)
    vip = threading.Thread(target=req, args=("b", 99, 8))
    vip.start()
    for t in flood + [vip]:
        t.join(timeout=180)
    # The minority tenant got through despite the flood.
    assert 200 in results["b"], results
    assert all(c in (200, 429, 503) for cs in results.values()
               for c in cs), results


def test_generic_route_still_served_and_404s(ingress_cluster):
    """The pre-existing generic data path (route-prefix dispatch) rides
    the same admission + bounded pool; unknown routes still 404."""
    _, port = ingress_cluster

    @serve.deployment
    def adder(req):
        return {"sum": req["json"]["a"] + req["json"]["b"]}

    serve.run(adder.bind(), route_prefix="/add")
    deadline = time.time() + 30
    out = None
    while time.time() < deadline:
        try:
            with _post(port, "/add", {"a": 3, "b": 4}) as resp:
                out = json.loads(resp.read())
            break
        except urllib.error.HTTPError:
            time.sleep(0.3)
    assert out == {"sum": 7}
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/no-such-route", timeout=10)
    assert ei.value.code == 404
    serve.delete("adder")
