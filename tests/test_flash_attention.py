"""Flash attention kernel tests (pallas interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops.attention import mha_reference
from ray_tpu.ops.flash_attention import flash_attention


def _qkv(key, b=2, l=256, h=4, d=64, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (b, l, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = _qkv(jax.random.key(0))
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_flash_multiblock_seq():
    q, k, v = _qkv(jax.random.key(1), l=512)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128,
                          interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_gradients_match(causal):
    q, k, v = _qkv(jax.random.key(2), l=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


def test_flash_gradients_long_seq():
    """Pallas backward at seq 2048 (multi-block both ways) vs reference VJP."""
    q, k, v = _qkv(jax.random.key(4), b=1, l=2048, h=2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=128,
                                       block_k=128, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-4, rtol=1e-3)


def test_fallback_on_causal_cross_length():
    """causal with lq != lk must take the reference path (the blocked
    kernel's diagonal bookkeeping assumes square); regression for a NaN."""
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (1, 256, 2, 64))
    k = jax.random.normal(ks[1], (1, 128, 2, 64))
    v = jax.random.normal(ks[2], (1, 128, 2, 64))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    assert not np.any(np.isnan(np.asarray(out)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)


def test_fallback_on_ragged_seq():
    q, k, v = _qkv(jax.random.key(3), l=100)  # not a multiple of blocks
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
