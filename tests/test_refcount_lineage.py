"""Distributed refcounting + lineage reconstruction.

Reference parity targets: core_worker/reference_count.h:61 (ref lifetimes
drive store reclamation) and object_recovery_manager.h:41 + task resubmit
(lost objects rebuilt by re-running their producing task).
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def fast_free_cluster():
    """Single-node cluster with a short free grace so tests run quickly."""
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                       _system_config={"free_grace_s": 0.2,
                                      "refcount_flush_ms": 30})
    yield ctx
    ray_tpu.shutdown()


def _wait_until(pred, timeout=15.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_out_of_scope_ref_reclaims_store(fast_free_cluster):
    """Dropping the last ObjectRef frees the store copy without free()."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.require_worker()
    ref = ray_tpu.put(np.ones(1 << 20, np.uint8))  # 1 MiB
    oid = ref.binary()
    assert w.store.contains(oid)
    del ref
    gc.collect()
    _wait_until(lambda: not w.store.contains(oid),
                msg="store copy reclaimed after last ref died")


def test_live_ref_is_not_reclaimed(fast_free_cluster):
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.require_worker()
    ref = ray_tpu.put(np.ones(1 << 20, np.uint8))
    time.sleep(1.0)  # several grace windows
    assert w.store.contains(ref.binary())
    assert int(ray_tpu.get(ref)[0]) == 1


def test_task_result_reclaimed_after_drop(fast_free_cluster):
    from ray_tpu._private import worker as worker_mod

    @ray_tpu.remote
    def produce():
        return np.arange(1 << 18, dtype=np.uint8)

    w = worker_mod.require_worker()
    ref = produce.remote()
    assert ray_tpu.get(ref).shape == (1 << 18,)
    oid = ref.binary()
    assert w.store.contains(oid)
    del ref
    gc.collect()
    _wait_until(lambda: not w.store.contains(oid),
                msg="task result reclaimed")


def test_borrowed_ref_keeps_object_alive(fast_free_cluster):
    """A ref handed to an actor (pickled -> restored there) keeps the
    object alive after the driver's copy dies."""

    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.ref = None

        def hold(self, box):
            self.ref = box[0]  # nested ref: restored + increfed here
            return True

        def read(self):
            return int(ray_tpu.get(self.ref)[0])

    from ray_tpu._private import worker as worker_mod

    w = worker_mod.require_worker()
    h = Holder.remote()
    ref = ray_tpu.put(np.full(1 << 16, 7, np.uint8))
    oid = ref.binary()
    assert ray_tpu.get(h.hold.remote([ref]))
    del ref
    gc.collect()
    time.sleep(1.0)  # several grace windows: borrower must keep it alive
    assert w.store.contains(oid) or ray_tpu.get(h.read.remote()) == 7
    assert ray_tpu.get(h.read.remote()) == 7


@pytest.fixture
def two_node_cluster():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2})
    worker_node = cluster.add_node(num_cpus=2,
                                   labels={"zone": "b"})
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    yield cluster, worker_node
    ray_tpu.shutdown()
    cluster.shutdown()


def test_lineage_reconstruction_on_node_death(two_node_cluster):
    """An object whose only copy lived on a dead node is rebuilt by
    re-running its producing task on a surviving node."""
    cluster, worker_node = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray_tpu.remote(max_retries=2)
    def produce(seed):
        return np.full((1 << 16,), seed, np.uint8)

    ref = produce.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=worker_node.node_id, soft=False)).remote(9)
    assert int(ray_tpu.get(ref)[0]) == 9

    # Ensure the only copy is on the worker node, then kill that node.
    cluster.remove_node(worker_node)
    out = ray_tpu.get(ref, timeout=30)
    assert int(out[0]) == 9 and out.shape == (1 << 16,)


def test_chained_lineage_reconstruction(two_node_cluster):
    """Losing both links of a task chain rebuilds recursively."""
    cluster, worker_node = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    strat = NodeAffinitySchedulingStrategy(node_id=worker_node.node_id,
                                           soft=False)

    @ray_tpu.remote(max_retries=2)
    def base():
        return np.full((1 << 14,), 3, np.uint8)

    @ray_tpu.remote(max_retries=2)
    def double(x):
        return (x.astype(np.uint16) * 2).astype(np.uint8)

    a = base.options(scheduling_strategy=strat).remote()
    b = double.options(scheduling_strategy=strat).remote(a)
    assert int(ray_tpu.get(b)[0]) == 6
    cluster.remove_node(worker_node)
    out = ray_tpu.get(b, timeout=30)
    assert int(out[0]) == 6


def test_lost_put_object_fails_cleanly(two_node_cluster):
    """put() objects have no lineage: losing every copy surfaces a clear
    error instead of hanging."""
    cluster, worker_node = two_node_cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray_tpu.remote
    class Putter:
        def make(self):
            return [ray_tpu.put(np.ones(1 << 14, np.uint8))]

    p = Putter.options(scheduling_strategy=NodeAffinitySchedulingStrategy(
        node_id=worker_node.node_id, soft=False)).remote()
    (ref,) = ray_tpu.get(p.make.remote())
    cluster.remove_node(worker_node)
    with pytest.raises(Exception) as ei:
        ray_tpu.get(ref, timeout=20)
    assert "lost" in str(ei.value) or "Lost" in str(ei.value)


def test_actor_task_args_pinned_in_flight(fast_free_cluster):
    """An ObjectRef passed to a BUSY actor and immediately dropped by the
    caller must survive until the actor executes the task — the custody
    chain caller->NM->worker pins it past the free-grace window
    (regression: shuffle parts were freed while adds sat in actor
    queues)."""
    import gc
    import time

    @ray_tpu.remote
    class Slowpoke:
        def block(self, sec):
            time.sleep(sec)
            return "done"

        def read(self, arr):
            return int(np.asarray(arr).sum())

    a = Slowpoke.remote()
    ray_tpu.get(a.block.remote(0.0))   # actor up
    blocker = a.block.remote(2.0)      # occupy the actor > grace window
    payload = ray_tpu.put(np.ones(1024, np.int64))
    res = a.read.remote(payload)
    del payload                        # caller's last ref dies NOW
    gc.collect()
    assert ray_tpu.get(res, timeout=60) == 1024
    assert ray_tpu.get(blocker, timeout=60) == "done"
