"""raylint + lockdep tier-1 tests.

Three layers:
- fixture snippets per checker: minimal must-trigger and
  must-not-trigger cases, including the historical r7 findings
  reconstructed as fixtures (so the checkers that encode them regress
  loudly);
- the repo itself: zero non-baselined violations, and the ratchet
  failing on a seeded violation / a stale baseline entry;
- the runtime lockdep shim: a constructed AB/BA deadlock must be
  witnessed with the cycle reported.

Pure ``ast`` + threading — no jax, no cluster.
"""

import json
import threading

import pytest

from ray_tpu._private import lockdep
from ray_tpu._private.lint import core


def lint_tree(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint it as if it were
    the repo root."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return core.run_lint([str(tmp_path / "ray_tpu")], root=str(tmp_path),
                         rules=rules)


def rules_of(violations):
    return sorted({v.rule for v in violations})


NM = "ray_tpu/_private/node_manager.py"   # a control-plane path
COLL = "ray_tpu/parallel/collective.py"   # a gang path


# --------------------------------------------------------- unbounded-wait

def test_unbounded_wait_triggers(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import ray\n"
        "def supervisor(conn, ev, fut):\n"
        "    ray.get(fut)\n"
        "    conn.request('lease_worker', {})\n"
        "    ev.wait()\n"
        "    fut.result()\n"
    )})
    waits = [x for x in v if x.rule == "unbounded-wait"]
    assert len(waits) == 4, v
    assert {w.line for w in waits} == {3, 4, 5, 6}


def test_unbounded_wait_bounded_calls_pass(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import ray\n"
        "def supervisor(conn, ev, fut, t):\n"
        "    ray.get(fut, timeout=5)\n"
        "    conn.request('lease_worker', {}, timeout=t)\n"
        "    ev.wait(1.0)\n"
        "    fut.result(t)\n"
        "    d = {}\n"
        "    d.get('key')\n"          # dict.get: positional key, no wait
    )})
    assert [x for x in v if x.rule == "unbounded-wait"] == []


def test_unbounded_wait_r7a_deferred_lease_reply(tmp_path):
    # r7 finding (a), reconstructed: the caller awaited a deferred
    # worker-lease reply with no bound — a worker that hung during
    # startup wedged that shape's whole pipeline.
    v = lint_tree(tmp_path, {"ray_tpu/_private/lease.py": (
        "def _grant(self, shape):\n"
        "    fut = self._conn.request_nowait('lease_worker', shape)\n"
        "    return fut.result()\n"   # <- the hang
    )})
    assert rules_of(v) == ["unbounded-wait"]


def test_unbounded_wait_ignores_non_control_plane(tmp_path):
    v = lint_tree(tmp_path, {"ray_tpu/scripts/cli.py": (
        "def main(fut):\n"
        "    fut.result()\n"
    )}, rules={"unbounded-wait"})
    assert v == []


# ---------------------------------------------------- blocking-under-lock

def test_blocking_under_lock_direct_and_one_call_deep(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import subprocess, threading, time\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _spawn_worker(self):\n"
        "        return subprocess.Popen(['true'])\n"
        "    def bad_direct(self, conn):\n"
        "        with self._lock:\n"
        "            time.sleep(1)\n"
        "            conn.request('x', timeout=5)\n"
        "    def bad_via_helper(self):\n"
        "        with self._lock:\n"
        "            self._spawn_worker()\n"
    )})
    blocked = [x for x in v if x.rule == "blocking-under-lock"]
    assert len(blocked) == 3, v
    assert any("_spawn_worker" in x.message for x in blocked)


def test_blocking_outside_lock_and_condition_idiom_pass(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading, time\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cv = threading.Condition()\n"
        "    def ok(self):\n"
        "        with self._lock:\n"
        "            snapshot = 1\n"
        "        time.sleep(0.1)\n"          # outside the lock
        "    def ok_cv(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(1.0)\n"   # releases while waiting
    )}, rules={"blocking-under-lock"})
    assert v == []


# ----------------------------------------------------------- lock-order

def test_lock_order_ab_ba_cycle(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._spill_lock = threading.Lock()\n"
        "    def ab(self):\n"
        "        with self._lock:\n"
        "            with self._spill_lock:\n"
        "                pass\n"
        "    def ba(self):\n"
        "        with self._spill_lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )})
    cycles = [x for x in v if x.rule == "lock-order"
              and "cycle" in x.message]
    assert len(cycles) == 1
    assert "_lock" in cycles[0].message and "_spill_lock" in \
        cycles[0].message


def test_lock_order_consistent_nesting_passes(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._spill_lock = threading.Lock()\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            with self._spill_lock:\n"
        "                pass\n"
        "    def g(self):\n"
        "        with self._lock:\n"
        "            with self._spill_lock:\n"
        "                pass\n"
    )}, rules={"lock-order"})
    assert v == []


def test_lock_order_nonreentrant_self_nest_via_helper(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.helper()\n"
        "    def helper(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )}, rules={"lock-order"})
    assert len(v) == 1 and "re-acquired while held" in v[0].message


def test_lock_order_rlock_self_nest_passes(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.helper()\n"
        "    def helper(self):\n"
        "        with self._lock:\n"
        "            pass\n"
    )}, rules={"lock-order"})
    assert v == []


# --------------------------------------------------------- hold-release

R7C_LEAK = (
    # r7 finding (c), reconstructed: _spawn_worker raising after the
    # mirror-subtract leaked the hold; every failed spawn permanently
    # shrank the node's schedulable capacity.
    "class NodeManager:\n"
    "    def _on_lease_task(self, spec):\n"
    "        self._local_avail.subtract(spec.resources)\n"
    "        w = self._spawn_worker()\n"
    "        return w\n"
)

R7C_FIXED = (
    # The attached[]-guard retrofit PR 3 landed, in miniature.
    "class NodeManager:\n"
    "    def _on_lease_task(self, spec):\n"
    "        self._local_avail.subtract(spec.resources)\n"
    "        try:\n"
    "            w = self._spawn_worker()\n"
    "        except BaseException:\n"
    "            self._local_avail.release(spec.resources)\n"
    "            raise\n"
    "        return w\n"
)


def test_hold_release_r7c_leak_triggers(tmp_path):
    v = lint_tree(tmp_path, {NM: R7C_LEAK})
    holds = [x for x in v if x.rule == "hold-release"]
    assert len(holds) == 1 and "local-ledger hold" in holds[0].message


def test_hold_release_attached_guard_passes(tmp_path):
    v = lint_tree(tmp_path, {NM: R7C_FIXED}, rules={"hold-release"})
    assert v == []


def test_hold_release_custody_transfer_passes(tmp_path):
    # The sanctioned pattern: the hold is recorded in a *_held* registry
    # whose owner (task-done / death path) releases it later.
    v = lint_tree(tmp_path, {NM: (
        "class NodeManager:\n"
        "    def _on_lease_task(self, spec, tid):\n"
        "        self._res_held_tasks[tid] = dict(spec.resources)\n"
        "        self._local_avail.subtract(spec.resources)\n"
        "        w = self._spawn_worker()\n"
        "        return w\n"
    )}, rules={"hold-release"})
    assert v == []


def test_hold_release_chip_leak_triggers(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "class NodeManager:\n"
        "    def grab(self, k):\n"
        "        chips = self._acquire_chips(k)\n"
        "        if chips is None:\n"
        "            raise RuntimeError('no chips')\n"
        "        return chips\n"
    )})
    holds = [x for x in v if x.rule == "hold-release"]
    assert len(holds) == 1 and "chip hold" in holds[0].message


# ----------------------------------------------------- exception-swallow

def test_exception_swallow_triggers_and_handled_passes(tmp_path):
    v = lint_tree(tmp_path, {COLL: (
        "import logging\n"
        "logger = logging.getLogger('x')\n"
        "def bad(coord):\n"
        "    try:\n"
        "        coord.poll()\n"
        "    except Exception:\n"
        "        pass\n"
        "def ok_logged(coord):\n"
        "    try:\n"
        "        coord.poll()\n"
        "    except Exception:\n"
        "        logger.exception('poll failed')\n"
        "def ok_reraise(coord):\n"
        "    try:\n"
        "        coord.poll()\n"
        "    except Exception as e:\n"
        "        if 'gang' in str(e):\n"
        "            raise\n"
    )}, rules={"exception-swallow"})
    assert len(v) == 1 and v[0].line == 6


def test_exception_swallow_not_applied_outside_gang_paths(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "def shutdown(w):\n"
        "    try:\n"
        "        w.proc.kill()\n"
        "    except Exception:\n"
        "        pass\n"
    )}, rules={"exception-swallow"})
    assert v == []


# ---------------------------------------------------- config-knob-drift

def test_config_drift_triggers_on_reads_not_writes(tmp_path):
    v = lint_tree(tmp_path, {"ray_tpu/util/thing.py": (
        "import os\n"
        "a = os.environ.get('RAY_TPU_FOO')\n"
        "b = os.getenv('RAY_TPU_BAR', '1')\n"
        "c = os.environ['RAY_TPU_BAZ']\n"
        "os.environ['RAY_TPU_CHILD_VAR'] = 'x'\n"   # write: spawner-side
        "d = os.environ.get('OTHER_PREFIX')\n"       # not our namespace
    )})
    drift = [x for x in v if x.rule == "config-knob-drift"]
    assert {x.line for x in drift} == {2, 3, 4}


def test_config_drift_suppression_with_comment(tmp_path):
    v = lint_tree(tmp_path, {"ray_tpu/util/thing.py": (
        "import os\n"
        "# raylint: disable-next=config-knob-drift (bootstrap identity)\n"
        "a = os.environ.get('RAY_TPU_WORKER_ID')\n"
    )})
    assert v == []


def test_suppression_spans_multiline_comment(tmp_path):
    v = lint_tree(tmp_path, {"ray_tpu/util/thing.py": (
        "import os\n"
        "# raylint: disable-next=config-knob-drift (bootstrap\n"
        "# identity: several comment lines between the directive\n"
        "# and the statement it annotates)\n"
        "a = os.environ.get('RAY_TPU_WORKER_ID')\n"
    )})
    assert v == []


def test_bare_disable_without_rule_is_not_honored(tmp_path):
    v = lint_tree(tmp_path, {"ray_tpu/util/thing.py": (
        "import os\n"
        "a = os.environ.get('RAY_TPU_FOO')  # raylint: disable\n"
    )})
    assert rules_of(v) == ["config-knob-drift"]


# --------------------------------------------------- repo + the ratchet

def test_repo_is_clean_against_baseline():
    violations = core.run_lint()
    baseline = core.load_baseline()
    new, stale = core.diff_baseline(violations, baseline)
    assert new == [], "\n".join(str(v) for v in new)
    assert stale == [], stale


def test_ratchet_fails_on_seeded_violation(tmp_path):
    # Acceptance criterion: seed a ray.get without timeout into a
    # supervisor path and the ratchet must fail against the baseline.
    v = lint_tree(tmp_path, {NM: (
        "import ray\n"
        "def _supervisor_loop(fut):\n"
        "    return ray.get(fut)\n"
    )})
    new, stale = core.diff_baseline(v, core.load_baseline())
    assert len(new) == 1 and new[0].rule == "unbounded-wait"


def test_ratchet_fails_on_stale_baseline_entry(tmp_path):
    stale_baseline = {"unbounded-wait::ray_tpu/_private/gone.py::x = 1": 1}
    new, stale = core.diff_baseline(core.run_lint(), stale_baseline)
    assert stale == list(stale_baseline)


def test_baseline_identity_survives_line_churn(tmp_path):
    src = ("import ray\n"
           "def f(fut):\n"
           "    return ray.get(fut)\n")
    v1 = lint_tree(tmp_path, {NM: src})
    # same code shifted 10 lines down: same baseline key
    shifted = ("\n" * 10) + src
    v2 = lint_tree(tmp_path, {NM: shifted})
    assert v1[0].key == v2[0].key
    assert v1[0].line != v2[0].line


def test_cli_repo_clean_and_explain(capsys):
    # In-process (a fresh interpreter pays the environment's jax
    # preimport; the CLI logic is identical through main()).
    from ray_tpu._private.lint.__main__ import main

    assert main([]) == 0, capsys.readouterr().out
    capsys.readouterr()
    assert main(["--explain", "blocking-under-lock"]) == 0
    out = capsys.readouterr().out
    assert "r7" in out and "MSG_DONTWAIT" in out
    assert main(["--explain", "no-such-rule"]) == 2
    assert main(["--list-rules"]) == 0
    assert len(capsys.readouterr().out.strip().splitlines()) >= 6


def test_cli_ratchet_fails_on_stale_baseline(tmp_path, capsys):
    from ray_tpu._private.lint.__main__ import main

    stale = tmp_path / "baseline.json"
    stale.write_text(json.dumps({"version": 1, "entries": {
        "unbounded-wait::ray_tpu/_private/gone.py::x = 1": 1}}))
    assert main(["--baseline", str(stale)]) == 1
    assert "STALE" in capsys.readouterr().out


def test_every_rule_has_explain_text():
    for checker in core.all_checkers():
        assert checker.EXPLAIN.strip().startswith(checker.RULE)
        assert "Fix:" in checker.EXPLAIN or "fix" in checker.EXPLAIN.lower()


# ------------------------------- fixes surfaced by the initial sweep

def test_gcs_channel_request_is_bounded_by_default():
    # Failing-before: _GcsChannel.request defaulted to timeout=None, so
    # a wedged GCS parked the calling control thread forever (the
    # unbounded-wait finding over ~20 worker.py sites). Now the
    # gcs_rpc_timeout_s knob bounds it by default.
    import time

    from ray_tpu._private import protocol
    from ray_tpu._private.config import config
    from ray_tpu._private.worker import _GcsChannel

    black_hole = protocol.Server(lambda conn, mtype, payload, msg_id: None,
                                 name="black-hole")
    old = config.gcs_rpc_timeout_s
    config.set("gcs_rpc_timeout_s", 0.3)
    ch = None
    try:
        ch = _GcsChannel(black_hole.address, None, "t")
        t0 = time.time()
        with pytest.raises(TimeoutError):
            ch.request("never_answered", {})
        assert time.time() - t0 < 5.0
    finally:
        config.set("gcs_rpc_timeout_s", old)
        if ch is not None:
            ch.close()
        black_hole.close()


def test_gcs_channel_unbounded_sentinel_outlives_the_default_bound():
    # The explicit opt-out for server-parked waits (wait_for_objects
    # with no user deadline): a reply arriving AFTER the default bound
    # must still fulfill an UNBOUNDED request.
    import threading as _t
    import time

    from ray_tpu._private import protocol
    from ray_tpu._private.config import config
    from ray_tpu._private.worker import _GcsChannel

    def slow_handler(conn, mtype, payload, msg_id):
        _t.Timer(0.8, lambda: conn.reply(msg_id, "late")).start()

    srv = protocol.Server(slow_handler, name="slow")
    old = config.gcs_rpc_timeout_s
    config.set("gcs_rpc_timeout_s", 0.2)
    ch = None
    try:
        ch = _GcsChannel(srv.address, None, "t")
        assert ch.request("parked", {}, timeout=ch.UNBOUNDED) == "late"
    finally:
        config.set("gcs_rpc_timeout_s", old)
        if ch is not None:
            ch.close()
        srv.close()


def test_request_timeout_abandons_pending_slot():
    # With control RPCs bounded by default, a timed-out request must not
    # leave its future registered on the conn (one leaked entry per
    # timeout for the life of the connection, plus late replies
    # resolving into futures nobody holds).
    from ray_tpu._private import protocol

    black_hole = protocol.Server(lambda conn, mtype, payload, msg_id: None,
                                 name="black-hole-pending")
    conn = None
    try:
        conn = protocol.connect(black_hole.address)
        with pytest.raises(TimeoutError):
            conn.request("never_answered", {}, timeout=0.2)
        assert conn._pending == {}
    finally:
        if conn is not None:
            conn.close()
        black_hole.close()


def test_empty_env_string_means_unset():
    # `RAY_TPU_FOO= cmd` (set-but-empty) must resolve to the default,
    # not coerce "" (which crashes numeric knobs and silently flips
    # bool knobs to False — the old raw-read contract kept empty
    # enabled).
    import os as _os

    from ray_tpu._private.config import Config

    _os.environ["RAY_TPU_PROBE_EMPTY_BOOL"] = ""
    try:
        c = Config()
        c.define("probe_empty_bool", True, "probe")
        assert c.probe_empty_bool is True
    finally:
        del _os.environ["RAY_TPU_PROBE_EMPTY_BOOL"]


def test_migrated_env_knobs_are_registered():
    # Failing-before: these rode raw os.environ reads scattered over
    # four modules (the config-knob-drift findings); now they are typed
    # registry entries with docs and defaults.
    from ray_tpu._private.config import config

    # Defaults via the entry table, not live values — the suite itself
    # may run with RAY_TPU_LOCKDEP_ENABLED=1 (tier-1 does).
    e = config._entries
    assert e["gcs_rpc_timeout_s"].default == 60.0
    assert e["address"].default == ""
    assert e["store_so"].default == ""
    assert e["usage_stats_enabled"].default is True
    assert e["lockdep_enabled"].default is False
    for name in ("gcs_rpc_timeout_s", "address", "store_so",
                 "usage_stats_enabled", "lockdep_enabled"):
        assert e[name].doc, name


def test_usage_stats_toggle_reads_the_registry():
    from ray_tpu._private import usage
    from ray_tpu._private.config import config

    old = config.usage_stats_enabled
    try:
        config.set("usage_stats_enabled", False)
        assert usage.usage_stats_enabled() is False
        config.set("usage_stats_enabled", True)
        assert usage.usage_stats_enabled() is True
    finally:
        config.set("usage_stats_enabled", old)


# ------------------------------------------------------------- lockdep

def test_lockdep_witnesses_ab_ba_cycle():
    was_installed = lockdep.installed()
    lockdep.install()
    lockdep.reset()
    try:
        A = lockdep.tracked(key="fixture:A")
        B = lockdep.tracked(key="fixture:B")

        def order(first, second):
            with first:
                with second:
                    pass

        t1 = threading.Thread(target=order, args=(A, B))
        t1.start()
        t1.join()
        t2 = threading.Thread(target=order, args=(B, A))
        t2.start()
        t2.join()

        found = lockdep.take_violations()
        assert len(found) == 1, found
        witness = found[0]
        assert "fixture:A" in witness.cycle and "fixture:B" in witness.cycle
        # The cycle closes back on itself and both edges carry sites.
        assert witness.cycle[0] == witness.cycle[-1]
        assert len(witness.edge_sites) == len(witness.cycle) - 1
        assert all(s != "?" for s in witness.edge_sites)
        assert "lock-order cycle" in str(witness)
    finally:
        lockdep.reset()
        if not was_installed:
            lockdep.uninstall()


def test_lockdep_consistent_order_and_recursion_are_clean():
    was_installed = lockdep.installed()
    lockdep.install()
    lockdep.reset()
    try:
        A = lockdep.tracked(key="fixture:A2")
        B = lockdep.tracked(key="fixture:B2")
        # Reentrant inner lock: the recursion case below re-acquires the
        # SAME instance on one thread, which a plain Lock would turn
        # into an immediate self-deadlock (the very bug class under
        # test — rediscovered live by this fixture's first draft).
        R = lockdep.tracked(threading.RLock(), key="fixture:R")
        for _ in range(3):
            with A:
                with B:
                    pass
        with R:
            with R:   # same instance: recursion, no self-edge
                pass
        assert lockdep.take_violations() == []
        graph = lockdep.graph_snapshot()
        assert "fixture:B2" in graph.get("fixture:A2", set())
    finally:
        lockdep.reset()
        if not was_installed:
            lockdep.uninstall()


def test_lockdep_trylock_creates_no_blocking_edge():
    # acquire(blocking=False) can never wait, so it can never close a
    # deadlock cycle — the protocol layer's inline-send fast path
    # (acquire(False) on _write_lock under NM handlers that hold the
    # NM lock) vs the writer thread's close() path is the real-world
    # benign inversion this encodes. A trylock-HELD lock is still a
    # valid source of edges for later blocking acquires.
    was_installed = lockdep.installed()
    lockdep.install()
    lockdep.reset()
    try:
        A = lockdep.tracked(key="fixture:TA")
        B = lockdep.tracked(key="fixture:TB")
        C = lockdep.tracked(key="fixture:TC")

        def blocking_ab():
            with A:
                with B:
                    pass

        t = threading.Thread(target=blocking_ab)
        t.start()
        t.join()
        # Reverse order, but via trylock: no B->A edge, no cycle.
        with B:
            assert A.acquire(blocking=False)
            A.release()
        assert lockdep.take_violations() == []
        graph = lockdep.graph_snapshot()
        assert "fixture:TA" not in graph.get("fixture:TB", set())
        # Held-side still works: trylock-held A + blocking C = A->C.
        assert A.acquire(blocking=False)
        try:
            with C:
                pass
        finally:
            A.release()
        assert "fixture:TC" in lockdep.graph_snapshot().get(
            "fixture:TA", set())
    finally:
        lockdep.reset()
        if not was_installed:
            lockdep.uninstall()


def test_lockdep_condition_over_tracked_lock():
    was_installed = lockdep.installed()
    lockdep.install()
    lockdep.reset()
    try:
        cv = threading.Condition(lockdep.tracked(key="fixture:CVL"))
        hits = []

        def waiter():
            with cv:
                cv.wait(timeout=5)
                hits.append(1)

        t = threading.Thread(target=waiter)
        t.start()
        import time
        time.sleep(0.2)
        with cv:
            cv.notify_all()
        t.join(timeout=5)
        assert hits == [1]
        assert lockdep.take_violations() == []
    finally:
        lockdep.reset()
        if not was_installed:
            lockdep.uninstall()


def test_lockdep_factory_wraps_only_ray_tpu_locks():
    was_installed = lockdep.installed()
    lockdep.install()
    try:
        from ray_tpu._private.config import Config

        c = Config()   # Config.__init__ runs in a ray_tpu file
        assert type(c._lock).__name__ == "_TrackedLock"
        here = threading.Lock()   # this test file is outside ray_tpu/
        assert type(here).__name__ != "_TrackedLock"
    finally:
        if not was_installed:
            lockdep.uninstall()
        lockdep.reset()
        lockdep.take_violations()


# ----------------------------------------- GCS shard locks (SCALE_r06)

GCSF = "ray_tpu/_private/gcs.py"   # a control-plane path


def test_shard_locks_are_distinct_identities(tmp_path):
    """The four GCS shard locks resolve to distinct creation-site
    identities, so a rank inversion between any two is a reportable
    cycle — the checker must NOT conflate them into one node (which
    would reduce every inversion to an invisible self-edge)."""
    v = lint_tree(tmp_path, {GCSF: (
        "import threading\n"
        "class GcsServer:\n"
        "    def __init__(self):\n"
        "        self._sched_lock = threading.RLock()\n"
        "        self._actor_lock = threading.RLock()\n"
        "        self._obj_lock = threading.RLock()\n"
        "        self._kv_lock = threading.RLock()\n"
        "    def forward(self):\n"
        "        with self._sched_lock:\n"
        "            with self._actor_lock:\n"
        "                with self._obj_lock:\n"
        "                    pass\n"
        "    def kv_forward(self):\n"
        "        with self._obj_lock:\n"
        "            with self._kv_lock:\n"
        "                pass\n"
    )}, rules={"lock-order"})
    assert v == []   # rank-forward nesting only: clean


def test_shard_lock_rank_inversion_is_flagged(tmp_path):
    """A handler nesting rank-backward (obj shard -> actor shard, e.g.
    a scheduler pass invoked while the object shard is held) closes a
    cycle against the canonical sched<actor<obj order and must be
    reported — this is the exact shape raylint caught in review while
    this PR's sharding landed."""
    v = lint_tree(tmp_path, {GCSF: (
        "import threading\n"
        "class GcsServer:\n"
        "    def __init__(self):\n"
        "        self._actor_lock = threading.RLock()\n"
        "        self._obj_lock = threading.RLock()\n"
        "    def _schedule_actor(self):\n"
        "        with self._actor_lock:\n"
        "            with self._obj_lock:\n"
        "                pass\n"
        "    def _submit_holding_obj(self):\n"
        "        with self._obj_lock:\n"
        "            self._try_schedule()\n"
        "    def _try_schedule(self):\n"
        "        with self._actor_lock:\n"
        "            pass\n"
    )}, rules={"lock-order"})
    cycles = [x for x in v if x.rule == "lock-order"
              and "cycle" in x.message]
    assert len(cycles) == 1
    assert "_actor_lock" in cycles[0].message
    assert "_obj_lock" in cycles[0].message


def test_repo_gcs_shard_locks_registered():
    """The real gcs.py registers all four shard locks as separate
    reentrant identities (guards against a refactor collapsing them)."""
    project = core.Project(core.collect_sources(
        [core.REPO_ROOT + "/ray_tpu/_private/gcs.py"]))
    reg = project.lock_registry()
    for name in ("_sched_lock", "_actor_lock", "_obj_lock", "_kv_lock"):
        lid = f"ray_tpu._private.gcs.GcsServer.{name}"
        assert lid in reg, f"missing shard lock identity {lid}"
        assert reg[lid]["reentrant"], f"{lid} must be an RLock"


# ---------------------------------------------- whole-program call graph

UTIL = "ray_tpu/util/helpers.py"          # NOT a control-plane path
SCHED = "ray_tpu/_private/sched.py"
OBJSTORE = "ray_tpu/_private/objstore.py"
INGRESS = "ray_tpu/serve/ingress/app.py"  # async-blocking scope


def test_crossmodule_blocking_under_lock_triggers(tmp_path):
    """A control-plane with-block calling into a helper MODULE whose
    function sleeps is flagged at the call site, chain attached."""
    v = lint_tree(tmp_path, {
        NM: (
            "import threading\n"
            "from ray_tpu.util import helpers\n"
            "class NodeManager:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def restart(self):\n"
            "        with self._lock:\n"
            "            helpers.settle()\n"
        ),
        UTIL: (
            "import time\n"
            "def settle():\n"
            "    time.sleep(1.0)\n"
        ),
    }, rules={"blocking-under-lock"})
    assert rules_of(v) == ["blocking-under-lock"], v
    assert v[0].path == NM and v[0].line == 8
    assert v[0].chain and any("time.sleep" in hop for hop in v[0].chain)


def test_crossmodule_blocking_under_lock_clean_helper_passes(tmp_path):
    v = lint_tree(tmp_path, {
        NM: (
            "import threading\n"
            "from ray_tpu.util import helpers\n"
            "class NodeManager:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def restart(self):\n"
            "        with self._lock:\n"
            "            helpers.settle()\n"
        ),
        UTIL: (
            "def settle():\n"
            "    return 2 + 2\n"
        ),
    }, rules={"blocking-under-lock"})
    assert v == [], v


def test_crossmodule_unbounded_wait_triggers(tmp_path):
    """A control-plane call into a non-control-plane helper that parks
    with no bound is flagged at the control-plane call site."""
    v = lint_tree(tmp_path, {
        NM: (
            "from ray_tpu.util import helpers\n"
            "def supervise(fut):\n"
            "    helpers.settle(fut)\n"
        ),
        UTIL: (
            "def settle(fut):\n"
            "    return fut.result()\n"
        ),
    }, rules={"unbounded-wait"})
    assert rules_of(v) == ["unbounded-wait"], v
    assert v[0].path == NM and v[0].line == 3
    assert v[0].chain and any("fut.result" in hop for hop in v[0].chain)


def test_crossmodule_unbounded_wait_bound_propagates(tmp_path):
    """Bounds propagate through the chain: a helper whose wait is bound
    by its own timeout param is unbounded exactly at call sites that
    don't supply one."""
    files = {
        UTIL: (
            "def settle(fut, timeout=None):\n"
            "    return fut.result(timeout)\n"
        ),
    }
    flagged = lint_tree(tmp_path, dict(files, **{NM: (
        "from ray_tpu.util import helpers\n"
        "def supervise(fut):\n"
        "    helpers.settle(fut)\n"              # no bound supplied
    )}), rules={"unbounded-wait"})
    assert rules_of(flagged) == ["unbounded-wait"], flagged
    clean = lint_tree(tmp_path, dict(files, **{NM: (
        "from ray_tpu.util import helpers\n"
        "def supervise(fut):\n"
        "    helpers.settle(fut, timeout=5.0)\n"  # caller bounds it
    )}), rules={"unbounded-wait"})
    assert clean == [], clean


def test_crossmodule_lock_order_try_schedule_inversion(tmp_path):
    """The two-module inversion the old one-file pass could never see:
    the object store calls back into the scheduler while holding its own
    lock, while the scheduler calls into the object store under its —
    obj->sched vs sched->obj, visible only through the call graph."""
    v = lint_tree(tmp_path, {
        SCHED: (
            "import threading\n"
            "from ray_tpu._private import objstore\n"
            "_sched_lock = threading.Lock()\n"
            "def _try_schedule():\n"
            "    with _sched_lock:\n"
            "        objstore.release_obj()\n"
        ),
        OBJSTORE: (
            "import threading\n"
            "from ray_tpu._private import sched\n"
            "_obj_lock = threading.Lock()\n"
            "def release_obj():\n"
            "    with _obj_lock:\n"
            "        pass\n"
            "def on_task_done():\n"
            "    with _obj_lock:\n"
            "        sched._try_schedule()\n"
        ),
    }, rules={"lock-order"})
    cycles = [x for x in v if "cycle" in x.message]
    assert len(cycles) == 1, v
    assert "_sched_lock" in cycles[0].message
    assert "_obj_lock" in cycles[0].message
    assert cycles[0].chain, "cycle must carry its witness chain"


def test_crossmodule_lock_order_consistent_nesting_passes(tmp_path):
    """Same two modules, but the callback happens AFTER the object lock
    is released — no inversion, no finding."""
    v = lint_tree(tmp_path, {
        SCHED: (
            "import threading\n"
            "from ray_tpu._private import objstore\n"
            "_sched_lock = threading.Lock()\n"
            "def _try_schedule():\n"
            "    with _sched_lock:\n"
            "        objstore.release_obj()\n"
        ),
        OBJSTORE: (
            "import threading\n"
            "from ray_tpu._private import sched\n"
            "_obj_lock = threading.Lock()\n"
            "def release_obj():\n"
            "    with _obj_lock:\n"
            "        pass\n"
            "def on_task_done():\n"
            "    with _obj_lock:\n"
            "        pass\n"
            "    sched._try_schedule()\n"
        ),
    }, rules={"lock-order"})
    assert [x for x in v if "cycle" in x.message] == [], v


# ------------------------------------------------------- async-blocking

def test_async_blocking_through_helper_module(tmp_path):
    """An async ingress handler reaching time.sleep through a helper
    MODULE is a finding — the loop stall is two files away."""
    v = lint_tree(tmp_path, {
        INGRESS: (
            "from ray_tpu.util import helpers\n"
            "async def handle(request):\n"
            "    helpers.warmup()\n"
        ),
        UTIL: (
            "import time\n"
            "def warmup():\n"
            "    time.sleep(0.5)\n"
        ),
    }, rules={"async-blocking"})
    assert rules_of(v) == ["async-blocking"], v
    assert v[0].path == INGRESS and v[0].line == 3
    assert v[0].chain and any("time.sleep" in hop for hop in v[0].chain)


def test_async_blocking_awaited_and_compute_pass(tmp_path):
    """Awaited helpers and pure-compute helpers do not stall the loop."""
    v = lint_tree(tmp_path, {
        INGRESS: (
            "import asyncio\n"
            "from ray_tpu.util import helpers\n"
            "async def handle(request):\n"
            "    await asyncio.sleep(0)\n"
            "    return helpers.shape(request)\n"
        ),
        UTIL: (
            "def shape(request):\n"
            "    return len(request)\n"
        ),
    }, rules={"async-blocking"})
    assert v == [], v


def test_async_blocking_bounded_wait_still_flagged(tmp_path):
    """A BOUNDED wait still blocks the loop: timeout= does not discharge
    this rule (unlike unbounded-wait)."""
    v = lint_tree(tmp_path, {INGRESS: (
        "async def handle(request, fut):\n"
        "    return fut.result(timeout=5)\n"
    )}, rules={"async-blocking"})
    assert rules_of(v) == ["async-blocking"], v


def test_async_blocking_out_of_scope_sync_tier_passes(tmp_path):
    """async defs outside the asyncio tier are not this rule's business
    (their sync call chains are covered by the other checkers)."""
    v = lint_tree(tmp_path, {"ray_tpu/train/loop.py": (
        "import time\n"
        "async def train_step():\n"
        "    time.sleep(0.1)\n"
    )}, rules={"async-blocking"})
    assert v == [], v


def test_async_blocking_loop_safe_boundary_declaration(tmp_path):
    """A helper that detects the loop and defers to an executor declares
    itself loop-safe ON ITS DEF LINE; every async caller is covered."""
    v = lint_tree(tmp_path, {
        INGRESS: (
            "from ray_tpu.util import helpers\n"
            "async def handle(request):\n"
            "    helpers.emit()\n"
        ),
        UTIL: (
            "import asyncio\n"
            "import time\n"
            "# raylint: disable-next=async-blocking (defers to the\n"
            "# default executor when called on a loop thread)\n"
            "def emit():\n"
            "    try:\n"
            "        loop = asyncio.get_running_loop()\n"
            "    except RuntimeError:\n"
            "        _flush()\n"
            "        return\n"
            "    loop.run_in_executor(None, _flush)\n"
            "def _flush():\n"
            "    time.sleep(0.5)\n"
        ),
    }, rules={"async-blocking"})
    assert v == [], v


# ---------------------------------------------------- graph resolution

def test_callgraph_resolves_import_alias(tmp_path):
    v = lint_tree(tmp_path, {
        NM: (
            "import ray_tpu.util.helpers as hp\n"
            "def supervise(fut):\n"
            "    hp.settle(fut)\n"
        ),
        UTIL: (
            "def settle(fut):\n"
            "    return fut.result()\n"
        ),
    }, rules={"unbounded-wait"})
    assert rules_of(v) == ["unbounded-wait"], v


def test_callgraph_resolves_self_method_dispatch(tmp_path):
    """self.-dispatch: the blocking op is two METHOD hops away."""
    v = lint_tree(tmp_path, {NM: (
        "import threading, time\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def restart(self):\n"
        "        with self._lock:\n"
        "            self._drain()\n"
        "    def _drain(self):\n"
        "        self._settle()\n"
        "    def _settle(self):\n"
        "        time.sleep(1.0)\n"
    )}, rules={"blocking-under-lock"})
    assert rules_of(v) == ["blocking-under-lock"], v
    assert v[0].line == 7


def test_callgraph_cycle_terminates_and_propagates(tmp_path):
    """Mutually recursive helpers must not hang the fixed point, and
    their ops still propagate out of the cycle."""
    v = lint_tree(tmp_path, {
        NM: (
            "from ray_tpu.util import helpers\n"
            "def supervise(fut):\n"
            "    helpers.ping(fut)\n"
        ),
        UTIL: (
            "def ping(fut):\n"
            "    pong(fut)\n"
            "def pong(fut):\n"
            "    ping(fut)\n"
            "    return fut.result()\n"
        ),
    }, rules={"unbounded-wait"})
    assert rules_of(v) == ["unbounded-wait"], v


def test_depth_knob_bounds_propagation(tmp_path):
    """depth=1 approximates the old one-call-deep pass; the default full
    fixed point sees through arbitrarily long chains."""
    files = {
        NM: (
            "from ray_tpu.util import helpers\n"
            "def supervise(fut):\n"
            "    helpers.mid(fut)\n"
        ),
        UTIL: (
            "def mid(fut):\n"
            "    return deep(fut)\n"
            "def deep(fut):\n"
            "    return deeper(fut)\n"
            "def deeper(fut):\n"
            "    return fut.result()\n"
        ),
    }
    full = lint_tree(tmp_path, files, rules={"unbounded-wait"})
    assert rules_of(full) == ["unbounded-wait"], full
    for rel, srctext in files.items():
        (tmp_path / rel).write_text(srctext)
    shallow = core.run_lint([str(tmp_path / "ray_tpu")],
                            root=str(tmp_path),
                            rules={"unbounded-wait"}, depth=1)
    assert shallow == [], shallow


# ----------------------------------------------------- stale-suppression

def test_stale_suppression_flags_dead_comment(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "def fine(fut):\n"
        "    # raylint: disable-next=unbounded-wait (stale claim)\n"
        "    return fut.result(5)\n"   # bounded: rule does not fire
    )})
    stale = [x for x in v if x.rule == "stale-suppression"]
    assert len(stale) == 1, v
    assert "unbounded-wait" in stale[0].message


def test_stale_suppression_quiet_when_suppression_absorbs(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "def reader(fut):\n"
        "    # raylint: disable-next=unbounded-wait (dedicated reader)\n"
        "    return fut.result()\n"
    )})
    assert [x for x in v if x.rule == "stale-suppression"] == [], v
    assert [x for x in v if x.rule == "unbounded-wait"] == [], v


def test_stale_suppression_flags_unknown_rule_name(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "def reader(fut):\n"
        "    # raylint: disable-next=unbonded-wait (typo)\n"
        "    return fut.result()\n"
    )})
    stale = [x for x in v if x.rule == "stale-suppression"]
    assert len(stale) == 1, v
    assert "unknown rule" in stale[0].message


def test_stale_suppression_skips_rules_that_did_not_run(tmp_path):
    """A --rule-filtered run cannot judge other rules' suppressions."""
    v = lint_tree(tmp_path, {NM: (
        "def fine(fut):\n"
        "    # raylint: disable-next=unbounded-wait (stale claim)\n"
        "    return fut.result(5)\n"
    )}, rules={"stale-suppression", "lock-order"})
    assert [x for x in v if x.rule == "stale-suppression"] == [], v


# ------------------------------------------------------- lock-ambiguous

def test_lock_ambiguous_untyped_receiver_flagged(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "class GcsTable:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "def snapshot(nm):\n"
        "    with nm._lock:\n"
        "        return 1\n"
    )}, rules={"lock-ambiguous"})
    assert rules_of(v) == ["lock-ambiguous"], v
    assert "nm._lock" in v[0].message


def test_lock_ambiguous_annotation_disambiguates(tmp_path):
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "class GcsTable:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "def snapshot(nm: NodeManager):\n"
        "    with nm._lock:\n"
        "        return 1\n"
    )}, rules={"lock-ambiguous"})
    assert v == [], v


def test_ambiguous_lock_identity_does_not_conflate(tmp_path):
    """The historical failure mode: an unresolvable attr lock collapsed
    every ``_lock``-defining class into one graph node, manufacturing
    false cycles. The site-scoped identity must NOT create a cycle with
    the real locks' edges."""
    v = lint_tree(tmp_path, {NM: (
        "import threading\n"
        "class NodeManager:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._aux = threading.Lock()\n"
        "    def a(self):\n"
        "        with self._lock:\n"
        "            with self._aux:\n"
        "                pass\n"
        "class GcsTable:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._aux = threading.Lock()\n"
        "def poke(thing):\n"
        "    with thing._aux:\n"       # untyped: NodeManager? GcsTable?
        "        with thing._lock:\n"  # inverted order vs a()
        "            pass\n"
    )}, rules={"lock-order"})
    assert [x for x in v if "cycle" in x.message] == [], v


# ------------------------------------------------- collect_sources scope

def test_collect_sources_includes_foreign_lint_dirs(tmp_path):
    """Only the linter's OWN package is exempt from linting — a product
    directory that happens to be named ``lint`` is still linted."""
    rel = "ray_tpu/foo/lint/bar.py"
    p = tmp_path / rel
    p.parent.mkdir(parents=True)
    p.write_text("def f():\n    return 1\n")
    srcs = core.collect_sources([str(tmp_path / "ray_tpu")],
                                root=str(tmp_path))
    assert [s.rel for s in srcs] == [rel]


def test_collect_sources_excludes_own_lint_package():
    srcs = core.collect_sources()
    rels = [s.rel for s in srcs]
    assert not any(r.startswith("ray_tpu/_private/lint/") for r in rels)
    assert any(r == "ray_tpu/_private/lockdep.py" for r in rels)


# ------------------------------------------------------------------ CLI

def _run_cli(argv, monkeypatch=None, capsys=None):
    from ray_tpu._private.lint import __main__ as cli

    rc = cli.main(argv)
    out = capsys.readouterr().out if capsys is not None else ""
    return rc, out


def test_cli_json_includes_call_path(tmp_path, monkeypatch, capsys):
    import json as _json

    from ray_tpu._private.lint import __main__ as cli

    for rel, text in {
        NM: (
            "from ray_tpu.util import helpers\n"
            "def supervise(fut):\n"
            "    helpers.settle(fut)\n"
        ),
        UTIL: (
            "def settle(fut):\n"
            "    return fut.result()\n"
        ),
    }.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    # run_lint's root default is bound to the real repo; aim the CLI's
    # call at the fixture tree instead.
    real_run_lint = core.run_lint
    monkeypatch.setattr(
        cli.core, "run_lint",
        lambda paths, **kw: real_run_lint(paths, root=str(tmp_path),
                                          rules=kw.get("rules"),
                                          depth=kw.get("depth")))
    rc, out = _run_cli(
        [str(tmp_path / "ray_tpu"), "--no-baseline", "--json",
         "--rule", "unbounded-wait"], capsys=capsys)
    assert rc == 1
    doc = _json.loads(out)
    (v,) = doc["violations"]
    assert v["rule"] == "unbounded-wait" and v["path"] == NM
    assert v["chain"] and any("fut.result" in hop for hop in v["chain"])


def test_cli_emit_lock_graph_shape(capsys):
    import json as _json

    from ray_tpu._private.lint import __main__ as cli

    rc = cli.main(["--emit-lock-graph"])
    out = capsys.readouterr().out
    assert rc == 0
    doc = _json.loads(out)
    assert doc["version"] == 1
    assert doc["locks"] and doc["edges"]
    for lid, info in doc["locks"].items():
        assert ":" in info["site"] and isinstance(info["reentrant"], bool)
    known = set(doc["locks"])
    for e in doc["edges"]:
        assert e["outer"] in known or e["outer"].startswith("?")
        assert e["at"].count(":") == 1 and e["chain"]


def test_cli_changed_only_filters_by_git_diff(monkeypatch, capsys):
    from ray_tpu._private.lint import __main__ as cli

    fake = [
        core.Violation("unbounded-wait", "ray_tpu/_private/gcs.py", 10,
                       "m", "s"),
        core.Violation("unbounded-wait", "ray_tpu/_private/lease.py", 20,
                       "m", "s"),
    ]
    monkeypatch.setattr(cli.core, "run_lint",
                        lambda *a, **k: list(fake))
    monkeypatch.setattr(cli, "_changed_files",
                        lambda root: {"ray_tpu/_private/lease.py"})
    rc = cli.main(["--no-baseline", "--changed-only"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "lease.py:20" in out and "gcs.py:10" not in out
    assert "raylint: 1 violation" in out


def test_cli_changed_files_reads_git(tmp_path):
    import subprocess

    from ray_tpu._private.lint import __main__ as cli

    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True,
                       env={"GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                            "GIT_COMMITTER_NAME": "t",
                            "GIT_COMMITTER_EMAIL": "t@t",
                            "HOME": str(tmp_path), "PATH": "/usr/bin:/bin"})

    git("init", "-q", "-b", "main")
    (tmp_path / "a.py").write_text("x = 1\n")
    git("add", "a.py")
    git("commit", "-qm", "seed")
    (tmp_path / "b.py").write_text("y = 2\n")
    git("add", "b.py")
    assert cli._changed_files(str(tmp_path)) == {"b.py"}
