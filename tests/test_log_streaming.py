"""Log streaming to driver (reference: _private/log_monitor.py:104 —
worker stdout/stderr tailed from session files and republished on the
driver with a worker-identity prefix)."""

import os
import sys
import time

import ray_tpu


def _drain_until(capfd, markers, timeout=15.0):
    """Accumulate captured driver output until every marker appeared."""
    if isinstance(markers, str):
        markers = [markers]
    buf_out, buf_err = "", ""
    deadline = time.time() + timeout
    while time.time() < deadline:
        out, err = capfd.readouterr()
        buf_out += out
        buf_err += err
        if all(m in buf_out or m in buf_err for m in markers):
            return buf_out, buf_err
        time.sleep(0.2)
    raise AssertionError(
        f"markers {markers!r} never reached the driver; "
        f"stdout={buf_out[-500:]!r} stderr={buf_err[-500:]!r}")


def test_print_in_task_reaches_driver(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=True)
    try:
        @ray_tpu.remote
        def chatty():
            print("stream-me-MARKER-out")
            print("stream-me-MARKER-err", file=sys.stderr)
            return os.getpid()

        pid = ray_tpu.get(chatty.remote(), timeout=60)
        out, err = _drain_until(
            capfd, ["stream-me-MARKER-out", "stream-me-MARKER-err"])
        line = next(ln for ln in out.splitlines()
                    if "stream-me-MARKER-out" in ln)
        # Prefixed with the producing worker's identity.
        assert f"pid={pid}" in line and line.startswith("(")
        # stderr lines land on the driver's stderr.
        assert "stream-me-MARKER-err" in err
    finally:
        ray_tpu.shutdown()


def test_log_to_driver_false_stays_quiet(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=False)
    try:
        @ray_tpu.remote
        def chatty():
            print("should-not-appear-MARKER")
            return 1

        assert ray_tpu.get(chatty.remote(), timeout=60) == 1
        time.sleep(1.5)
        out, err = capfd.readouterr()
        assert "should-not-appear-MARKER" not in out + err
    finally:
        ray_tpu.shutdown()


def test_actor_prints_reach_driver(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=True)
    try:
        @ray_tpu.remote
        class Talker:
            def say(self, msg):
                print(f"actor-says-{msg}")
                return True

        t = Talker.remote()
        assert ray_tpu.get(t.say.remote("MARKER42"), timeout=60)
        _drain_until(capfd, "actor-says-MARKER42")
    finally:
        ray_tpu.shutdown()
