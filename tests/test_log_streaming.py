"""Log streaming to driver (reference: _private/log_monitor.py:104 —
worker stdout/stderr tailed from session files and republished on the
driver with a worker-identity prefix)."""

import os
import sys
import time

import ray_tpu


def _drain_until(capfd, markers, timeout=15.0):
    """Accumulate captured driver output until every marker appeared."""
    if isinstance(markers, str):
        markers = [markers]
    buf_out, buf_err = "", ""
    deadline = time.time() + timeout
    while time.time() < deadline:
        out, err = capfd.readouterr()
        buf_out += out
        buf_err += err
        if all(m in buf_out or m in buf_err for m in markers):
            return buf_out, buf_err
        time.sleep(0.2)
    raise AssertionError(
        f"markers {markers!r} never reached the driver; "
        f"stdout={buf_out[-500:]!r} stderr={buf_err[-500:]!r}")


def test_print_in_task_reaches_driver(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=True)
    try:
        @ray_tpu.remote
        def chatty():
            print("stream-me-MARKER-out")
            print("stream-me-MARKER-err", file=sys.stderr)
            return os.getpid()

        pid = ray_tpu.get(chatty.remote(), timeout=60)
        out, err = _drain_until(
            capfd, ["stream-me-MARKER-out", "stream-me-MARKER-err"])
        line = next(ln for ln in out.splitlines()
                    if "stream-me-MARKER-out" in ln)
        # Prefixed with the producing worker's identity.
        assert f"pid={pid}" in line and line.startswith("(")
        # stderr lines land on the driver's stderr.
        assert "stream-me-MARKER-err" in err
    finally:
        ray_tpu.shutdown()


def test_log_to_driver_false_stays_quiet(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=False)
    try:
        @ray_tpu.remote
        def chatty():
            print("should-not-appear-MARKER")
            return 1

        assert ray_tpu.get(chatty.remote(), timeout=60) == 1
        time.sleep(1.5)
        out, err = capfd.readouterr()
        assert "should-not-appear-MARKER" not in out + err
    finally:
        ray_tpu.shutdown()


def test_actor_prints_reach_driver(capfd):
    ray_tpu.init(num_cpus=2, log_to_driver=True)
    try:
        @ray_tpu.remote
        class Talker:
            def say(self, msg):
                print(f"actor-says-{msg}")
                return True

        t = Talker.remote()
        assert ray_tpu.get(t.say.remote("MARKER42"), timeout=60)
        _drain_until(capfd, "actor-says-MARKER42")
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------- follow (tail -f)
# (ISSUE 12 satellite: bounded poll loop over agent byte-offset
# cursors — the carried ROADMAP log-streaming item)


def test_get_log_follow_streams_new_lines_in_order():
    """state.get_log(follow=True): the generator yields the initial
    tail, then ONLY new lines as they land — ordered, no duplicates —
    and close() stops it cleanly."""
    import threading

    from ray_tpu.experimental import state

    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        class Chatty:
            def __init__(self):
                self._stop = False

                def loop():
                    i = 0
                    while not self._stop and i < 200:
                        print(f"FOLLOW_MARK {i}", flush=True)
                        i += 1
                        time.sleep(0.1)

                threading.Thread(target=loop, daemon=True).start()

            def ping(self):
                return 1

            def stop(self):
                self._stop = True
                return True

        a = Chatty.remote()
        assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
        time.sleep(0.8)

        gen = state.get_log(actor_id=a._actor_id.hex(),
                            stream="stdout", follow=True,
                            interval_s=0.25)
        seen = []
        deadline = time.time() + 30
        for entry in gen:
            assert entry["stream"] == "stdout"
            assert "path" in entry and "next_offset" in entry
            seen += [ln for ln in entry.get("lines") or []
                     if ln.startswith("FOLLOW_MARK")]
            if len(seen) >= 10 or time.time() > deadline:
                break
        gen.close()
        assert len(seen) >= 10, seen
        nums = [int(ln.split()[1]) for ln in seen]
        assert nums == sorted(nums), "lines reordered"
        assert len(set(nums)) == len(nums), "duplicate lines"
        assert ray_tpu.get(a.stop.remote(), timeout=10)
    finally:
        ray_tpu.shutdown()


def test_follow_cursor_reads_only_complete_lines(tmp_path):
    """The agent's cursor read never splits a line: a partially-written
    trailing line stays unread until its newline lands."""
    from ray_tpu.dashboard.agent import read_file_from

    p = tmp_path / "w.log"
    p.write_bytes(b"one\ntwo\npart")
    lines, off = read_file_from(str(p), 0)
    assert lines == ["one", "two"]
    assert off == len(b"one\ntwo\n")
    # Nothing new and still no newline: cursor holds.
    lines, off2 = read_file_from(str(p), off)
    assert lines == [] and off2 == off
    # The newline lands: the held-back line is delivered once.
    with open(p, "ab") as f:
        f.write(b"ial\nthree\n")
    lines, off3 = read_file_from(str(p), off)
    assert lines == ["partial", "three"]
    # Truncation/rotation under the cursor restarts from 0.
    p.write_bytes(b"fresh\n")
    lines, off4 = read_file_from(str(p), off3)
    assert lines == ["fresh"] and off4 == len(b"fresh\n")
