from ray_tpu._private.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
)


def test_sizes():
    assert len(JobID.from_int(1).binary()) == 4
    job = JobID.from_int(7)
    actor = ActorID.of(job)
    assert len(actor.binary()) == 16
    task = TaskID.for_actor_task(actor)
    assert len(task.binary()) == 24
    obj = ObjectID.for_return(task, 0)
    assert len(obj.binary()) == 28


def test_embedding_roundtrip():
    job = JobID.from_int(42)
    actor = ActorID.of(job)
    assert actor.job_id() == job
    task = TaskID.for_actor_task(actor)
    assert task.actor_id() == actor
    assert task.job_id() == job
    obj = ObjectID.for_return(task, 3)
    assert obj.task_id() == task
    assert obj.index() == 3
    assert obj.job_id() == job
    assert not obj.is_put()
    put = ObjectID.for_put(task, 1)
    assert put.is_put()
    assert put.task_id() == task


def test_normal_task_has_nil_actor():
    job = JobID.from_int(1)
    task = TaskID.for_task(job)
    assert task.job_id() == job
    assert task.actor_id().binary()[:12] == b"\xff" * 12


def test_hex_and_equality():
    n = NodeID.from_random()
    assert NodeID.from_hex(n.hex()) == n
    assert n != NodeID.from_random()
    assert len({n, NodeID(n.binary())}) == 1


def test_nil():
    assert TaskID.nil().is_nil()
    assert not TaskID.for_task(JobID.from_int(1)).is_nil()


def test_placement_group_id():
    job = JobID.from_int(9)
    pg = PlacementGroupID.of(job)
    assert len(pg.binary()) == 18
    assert pg.job_id() == job


def test_pickle_roundtrip():
    import pickle

    job = JobID.from_int(5)
    obj = ObjectID.for_return(TaskID.for_task(job), 2)
    assert pickle.loads(pickle.dumps(obj)) == obj


def test_resource_set_fixed_point_exact_restoration():
    """VERDICT r3 weak #9: integer-scaled arithmetic — 10k fractional
    acquire/release cycles restore capacity EXACTLY (reference:
    raylet/scheduling/fixed_point.h)."""
    from ray_tpu._private.task_spec import ResourceSet

    rs = ResourceSet({"CPU": 4.0, "custom": 1.0})
    for _ in range(10_000):
        assert rs.acquire({"CPU": 0.1, "custom": 0.3})
        assert rs.acquire({"CPU": 0.2})
        rs.release({"CPU": 0.2})
        rs.release({"CPU": 0.1, "custom": 0.3})
    assert rs.to_dict() == {"CPU": 4.0, "custom": 1.0}
    # Full fractional packing works with zero drift: 40 x 0.1 CPU.
    for _ in range(40):
        assert rs.acquire({"CPU": 0.1})
    assert not rs.acquire({"CPU": 0.1})
    assert rs.get("CPU") == 0.0


def test_entropy_fork_safety():
    """Forked children must not replay the parent's buffered ID entropy."""
    import multiprocessing as mp

    from ray_tpu._private import ids

    ids.TaskID.for_task(ids.JobID.from_int(1))  # warm the buffer

    def child(q):
        q.put(ids.TaskID.for_task(ids.JobID.from_int(1)).binary())

    ctx = mp.get_context("fork")
    q = ctx.Queue()
    p = ctx.Process(target=child, args=(q,))
    p.start()
    child_id = q.get(timeout=10)
    p.join(timeout=10)
    parent_id = ids.TaskID.for_task(ids.JobID.from_int(1)).binary()
    assert child_id != parent_id
