"""Ape-X DQN: sharded prioritized replay with priority feedback
(reference: rllib/algorithms/apex_dqn/apex_dqn.py +
utils/replay_buffers/prioritized_replay_buffer.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import ApexDQNConfig
from ray_tpu.rllib.apex import _ReplayShard


def _cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_prioritized_shard_math():
    """Unit: sampling concentrates on high-priority entries; importance
    weights correct for the bias; priority updates take effect."""
    shard = _ReplayShard(capacity=64, obs_dim=2, alpha=1.0, eps=1e-6,
                         seed=0)
    batch = {"obs": np.zeros((10, 2), np.float32),
             "actions": np.arange(10, dtype=np.int32),
             "rewards": np.zeros(10, np.float32),
             "next_obs": np.zeros((10, 2), np.float32),
             "dones": np.zeros(10, np.float32)}
    prios = np.ones(10)
    prios[3] = 100.0     # one dominant transition
    shard.add_batch(batch, prios)
    out, idx = shard.sample(512, beta=1.0)
    frac_3 = float(np.mean(out["actions"] == 3))
    assert frac_3 > 0.7, frac_3          # p_3 = 100/109 ≈ 0.92
    # Importance weights: the over-sampled entry gets the SMALLEST
    # weight (max-normalized).
    w3 = out["weights"][out["actions"] == 3]
    w_other = out["weights"][out["actions"] != 3]
    assert w3.max() < w_other.min()
    # Feedback: flatten priorities -> sampling spreads back out.
    shard.update_priorities(np.arange(10), np.ones(10))
    out2, _ = shard.sample(512, beta=1.0)
    assert float(np.mean(out2["actions"] == 3)) < 0.3


def test_apex_end_to_end(ray_cluster):
    """Full Ape-X loop on CartPole: experience flows worker -> shard
    without a driver hop, the learner trains from shards and feeds
    priorities back, weights refresh, iterations overlap."""
    algo = (ApexDQNConfig(
                buffer_size=8000, learning_starts=200,
                train_batch_size=32, num_sgd_iters=8,
                num_replay_shards=2, rollout_fragment_length=100)
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2)
            .build())
    try:
        total_updates = 0
        for _ in range(4):
            m = algo.train()
            total_updates += m.get("learner_updates_this_iter", 0)
        assert m["replay_total"] >= 200
        assert m["replay_shards"] == 2
        assert total_updates > 0
        assert "td_abs" not in m        # internal key stripped
        # Both shards received experience (round-robin pushes).
        sizes = ray_tpu.get(
            [s.stats.remote() for s in algo.replay_shards])
        assert all(s["size"] > 0 for s in sizes), sizes
        # Priorities are non-uniform after feedback.
        assert any(s["prio_max"] > s["prio_mean"] for s in sizes), sizes
    finally:
        algo.stop()
