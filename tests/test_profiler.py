"""Cluster-wide sampling profiler (ISSUE 12): folded/speedscope
goldens, bounded-table eviction, sampler lifecycle across
init()/shutdown() cycles, the wedged-collective-rank capture, and the
GCS-subprocess self-profile over the bootstrap address."""

import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import profiler as profiler_mod
from ray_tpu._private.config import config
from ray_tpu._private.profiler import (
    SamplingProfiler,
    folded_lines,
    speedscope_document,
)
from ray_tpu.experimental import state


def _wait_for(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {msg}")


def _profiler_threads():
    return [t for t in threading.enumerate()
            if t.name == "rtpu-profiler" and t.is_alive()]


def _golden_busy_loop(stop):
    x = 0
    while not stop.is_set():
        x = (x + 1) % 1000003
    return x


# --------------------------------------------------------------- goldens


def test_folded_and_speedscope_golden():
    """A busy thread's hot function shows up in the folded output, and
    the merged speedscope document is schema-shaped and JSON-clean."""
    stop = threading.Event()
    t = threading.Thread(target=_golden_busy_loop, args=(stop,),
                         daemon=True, name="golden-busy")
    t.start()
    prof = SamplingProfiler()
    try:
        assert prof.start(hz=200)
        time.sleep(0.6)
        out = prof.collect(reset=True)
    finally:
        prof.stop()
        stop.set()
        t.join(timeout=5)
    assert out["samples"] > 0 and out["pid"] == os.getpid()
    busy = [s for s in out["stacks"] if "_golden_busy_loop" in s]
    assert busy, out["stacks"]
    # Folded keys lead with the thread name, frames root->leaf.
    assert any(s.startswith("golden-busy;") for s in busy), busy

    proc = dict(out, kind="worker", node_id="ab" * 6)
    lines = folded_lines([proc])
    assert lines and all(" " in ln for ln in lines)
    label, _, rest = lines[0].partition(";")
    assert label.startswith("worker node=")
    assert lines[0].rsplit(" ", 1)[1].isdigit()

    doc = speedscope_document([proc], name="golden")
    assert doc["$schema"].endswith("file-format-schema.json")
    assert doc["shared"]["frames"] and doc["profiles"]
    for p in doc["profiles"]:
        assert p["type"] == "sampled"
        assert len(p["samples"]) == len(p["weights"])
        assert p["endValue"] == sum(p["weights"])
        for sample in p["samples"]:
            for idx in sample:
                assert 0 <= idx < len(doc["shared"]["frames"])
    # One profile per (process, thread); the busy thread is among them.
    assert any("golden-busy" in p["name"] for p in doc["profiles"])
    json.loads(json.dumps(doc))   # JSON-clean end to end

    # The /metrics counters moved: samples were recorded.
    from ray_tpu.util.metrics import collect_samples

    names = {s["name"]: s["value"] for s in collect_samples()}
    assert names.get("profiler_samples_total", 0) >= out["samples"]


def test_cpu_mode_counts_idle_leaves_separately():
    """cpu mode: samples parked in blocking leaves (cv/event waits —
    pure-Python leaves; a C-level sleep leaves no Python leaf frame to
    classify) are accounted as idle, not attributed to the table."""
    prof = SamplingProfiler()
    stop = threading.Event()

    def sleeper():
        while not stop.is_set():
            stop.wait(0.05)   # leaf frame: threading Condition.wait

    t = threading.Thread(target=sleeper, daemon=True, name="idle-sleeper")
    t.start()
    try:
        assert prof.start(hz=200, mode="cpu")
        time.sleep(0.5)
        out = prof.collect(reset=True)
    finally:
        prof.stop()
        stop.set()
        t.join(timeout=5)
    assert out["mode"] == "cpu"
    assert out["idle_samples"] > 0
    assert not any(s.startswith("idle-sleeper;") for s in out["stacks"])


# ------------------------------------------------------- bounded table


def test_bounded_table_eviction_under_churning_stacks():
    """Deep/churning stacks: the folded table never exceeds its bound;
    evicted samples are accounted as dropped, never silently lost."""
    old = config.get("profiler_max_stacks")
    config.set("profiler_max_stacks", 16)
    try:
        prof = SamplingProfiler()
        for i in range(200):
            prof._add(f"churn;stack_{i:03d}", count=i + 1)
        out = prof.collect()
        assert len(out["stacks"]) <= 16
        assert out["samples"] == sum(range(1, 201))
        assert out["dropped"] > 0
        # Accounting closes: kept + dropped == recorded.
        assert sum(out["stacks"].values()) + out["dropped"] == \
            out["samples"]
        # Highest-count stacks survive (smallest-count eviction).
        assert "churn;stack_199" in out["stacks"]
    finally:
        config.set("profiler_max_stacks", old)


def test_deep_stack_truncated_with_marker():
    old = config.get("profiler_max_frames")
    config.set("profiler_max_frames", 8)
    try:
        prof = SamplingProfiler()
        stop = threading.Event()
        ready = threading.Event()

        def deep(n):
            if n > 0:
                return deep(n - 1)
            ready.set()
            stop.wait(10)

        t = threading.Thread(target=deep, args=(40,), daemon=True,
                             name="deep-rec")
        t.start()
        assert ready.wait(5)
        assert prof.start(hz=200)
        time.sleep(0.3)
        out = prof.collect(reset=True)
        prof.stop()
        stop.set()
        t.join(timeout=5)
        deep_stacks = [s for s in out["stacks"]
                       if s.startswith("deep-rec;")]
        assert deep_stacks
        for s in deep_stacks:
            frames = s.split(";")[1:]
            assert len(frames) <= 10   # max_frames + truncation marker
            assert "<truncated>" in frames[0]
    finally:
        config.set("profiler_max_frames", old)


# ------------------------------------------------------------ lifecycle


def test_sampler_start_stop_idempotent():
    prof = SamplingProfiler()
    before = len(_profiler_threads())
    assert prof.start()
    assert not prof.start()     # second start: no new thread
    assert len(_profiler_threads()) == before + 1
    prof.stop()
    prof.stop()                 # idempotent
    _wait_for(lambda: len(_profiler_threads()) == before, 5,
              "sampler thread join")


def test_always_on_no_thread_stacking_across_init_shutdown():
    """profiler_always_on across init()/shutdown() cycles: exactly one
    sampler while up, zero after shutdown — the PR 7 reporter-lifecycle
    contract, mirrored (no thread stacking)."""
    old = config.get("profiler_always_on")
    config.set("profiler_always_on", True)
    try:
        for _ in range(2):
            ray_tpu.init(num_cpus=1,
                         object_store_memory=64 * 1024 * 1024)
            assert len(_profiler_threads()) == 1
            ray_tpu.shutdown()
            _wait_for(lambda: len(_profiler_threads()) == 0, 5,
                      "sampler joined on shutdown")
    finally:
        config.set("profiler_always_on", old)


# ------------------------------------------------------- cluster capture


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_cluster_profile_covers_every_process_kind(ray_cluster):
    """One state.profile() window covers driver + node manager + GCS +
    workers, and the merged speedscope document holds them all."""
    @ray_tpu.remote
    def warm():
        return 1

    assert ray_tpu.get([warm.remote() for _ in range(2)],
                       timeout=60) == [1, 1]

    @ray_tpu.remote
    def spin(sec):
        t0 = time.time()
        x = 0
        while time.time() - t0 < sec:
            x += 1
        return x

    refs = [spin.remote(4.0) for _ in range(2)]
    time.sleep(0.3)
    t0 = time.time()
    processes = state.profile(duration_s=1.0)
    assert time.time() - t0 < 30
    kinds = {p.get("kind") for p in processes if not p.get("error")}
    assert {"gcs", "node_manager", "driver", "worker"} <= kinds, processes
    workers = [p for p in processes if p.get("kind") == "worker"]
    assert any("spin" in s for p in workers
               for s in (p.get("stacks") or {})), \
        "submit-phase hot path not attributed"
    doc = speedscope_document(processes)
    assert len(doc["profiles"]) >= len(
        [p for p in processes if not p.get("error")])
    ray_tpu.get(refs, timeout=60)


def test_worker_scoped_profile_filters(ray_cluster):
    @ray_tpu.remote
    class P:
        def ping(self):
            return 1

    a = P.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == 1
    aid = a._actor_id.hex()
    processes = state.profile(duration_s=0.3, actor_id=aid)
    ok = [p for p in processes if not p.get("error")]
    assert ok and all(p["kind"] == "worker" and p["actor_id"] == aid
                      for p in ok), processes


def test_wedged_collective_rank_still_profiles(ray_cluster):
    """The wedge case: a rank blocked inside a collective (peer never
    joins) still answers the profile verb — in-band, from its listener
    thread — and the capture attributes the collective frames."""
    @ray_tpu.remote
    class Rank:
        def __init__(self, rank):
            self.rank = rank

        def join(self, world):
            from ray_tpu.parallel import collective

            collective.init_collective_group(
                world, self.rank, backend="store",
                group_name="prof_wedge")
            return True

        def reduce(self):
            import numpy as np

            from ray_tpu.parallel import collective

            return collective.allreduce(
                np.ones(4), group_name="prof_wedge").tolist()

    r0, r1 = Rank.remote(0), Rank.remote(1)
    assert ray_tpu.get([r0.join.remote(2), r1.join.remote(2)],
                       timeout=60) == [True, True]
    wedged_ref = r0.reduce.remote()   # rank 1 never calls reduce
    time.sleep(1.5)                   # let rank 0 enter the op

    t0 = time.time()
    processes = state.profile(duration_s=1.0,
                              actor_id=r0._actor_id.hex())
    assert time.time() - t0 < 30      # bounded capture
    ok = [p for p in processes if not p.get("error")]
    assert ok, processes
    wedged = [p for p in ok
              if any("allreduce" in s or "_exchange" in s
                     for s in (p.get("stacks") or {}))]
    assert wedged, json.dumps(ok)[:2000]

    from ray_tpu.parallel import collective

    collective.poison_group("prof_wedge", "test teardown")
    with pytest.raises(Exception):
        ray_tpu.get(wedged_ref, timeout=30)


# --------------------------------------- GCS subprocess self-profile


def test_gcs_subprocess_self_profile_over_bootstrap_address():
    """The out-of-process GCS profiles ITSELF: a bare conn to the
    bootstrap address (no registration) asks for a gcs-scoped profile
    and gets back a window sampled in the GCS's own interpreter."""
    old = config.get("gcs_out_of_process")
    config.set("gcs_out_of_process", True)
    try:
        ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
        from ray_tpu._private import protocol
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.require_worker()
        gcs_pid = w.gcs.request("control_plane_stats",
                                timeout=30)["gcs_process"]["pid"]
        assert gcs_pid != os.getpid()
        conn = protocol.connect(w.gcs_address, name="prof-probe")
        try:
            out = conn.request("profile",
                               {"gcs": True, "duration_s": 0.5},
                               timeout=30)
        finally:
            conn.close()
        assert isinstance(out, list) and len(out) == 1, out
        prof = out[0]
        assert prof["kind"] == "gcs" and not prof.get("error")
        assert prof["pid"] == gcs_pid          # its OWN interpreter
        assert prof["samples"] > 0 and prof["stacks"]
        # The GCS serve loop is what a healthy idle GCS looks like.
        assert any("gcs" in s or "serve" in s or "wait" in s
                   for s in prof["stacks"])
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            config.set("gcs_out_of_process", old)


def test_profile_window_rearms_running_sampler_with_requested_knobs():
    """An always-on sampler (wall @ default hz) must honor a window's
    requested hz/mode — and resume its standing configuration after."""
    prof = SamplingProfiler()
    assert prof.start(hz=30, mode="wall")   # the standing always-on config
    try:
        out = prof.profile(duration_s=0.2, hz=200, mode="cpu")
        assert out["mode"] == "cpu" and out["hz"] == 200.0
        # Still running afterwards, restored to the standing knobs.
        assert prof.running
        assert prof._hz == 30.0 and prof._mode == "wall"
    finally:
        prof.stop()
