import os

from ray_tpu._private.config import Config


def test_defaults_and_set():
    c = Config()
    c.define("foo_ms", 100, "doc")
    assert c.foo_ms == 100
    c.set("foo_ms", "250")
    assert c.foo_ms == 250


def test_env_override():
    os.environ["RAY_TPU_BAR_ENABLED"] = "true"
    try:
        c = Config()
        c.define("bar_enabled", False)
        assert c.bar_enabled is True
    finally:
        del os.environ["RAY_TPU_BAR_ENABLED"]


def test_system_config_blob():
    c = Config()
    c.define("x", 1)
    c.define("y", 2.5)
    c.apply_system_config('{"x": 9, "y": 1.5, "unknown": 3}')
    assert c.x == 9 and c.y == 1.5


def test_global_config_has_core_knobs():
    from ray_tpu._private.config import config

    assert config.max_direct_call_object_size > 0
    assert 0 < config.scheduler_spread_threshold <= 1
