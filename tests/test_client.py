"""Remote-driver client proxy (reference: Ray Client,
util/client/server/proxier.py:113). The thin client runs in a separate
PROCESS with no cluster state — everything crosses one TCP connection."""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu
from ray_tpu.util.client import ClientServer, connect


@pytest.fixture
def proxy():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    srv = ClientServer(host="127.0.0.1")
    yield srv
    srv.close()
    ray_tpu.shutdown()


def test_client_roundtrip_same_process(proxy):
    c = connect(proxy.address)
    assert c.cluster_info["nodes"] >= 1

    ref = c.put({"k": [1, 2, 3]})
    assert c.get(ref) == {"k": [1, 2, 3]}

    out_ref = c.submit(lambda a, b: a * b, 6, 7)
    assert c.get(out_ref) == 42

    ready, not_ready = c.wait([ref, out_ref], num_returns=2, timeout=10)
    assert len(ready) == 2 and not not_ready

    class Counter:
        def __init__(self, start):
            self.n = start

        def incr(self, by=1):
            self.n += by
            return self.n

    h = c.create_actor(Counter, 10)
    assert c.get(h.incr()) == 11
    assert c.get(h.incr(by=5)) == 16
    c.kill_actor(h)
    c.disconnect()


def test_client_from_separate_process(proxy, tmp_path):
    """A genuinely external driver process: imports only the client."""
    script = tmp_path / "thin_driver.py"
    script.write_text(textwrap.dedent(f"""
        import sys
        sys.path.insert(0, {repr('/root/repo')})
        from ray_tpu.util.client import connect

        c = connect({proxy.address!r})
        ref = c.submit(lambda x: sum(range(x)), 10)
        assert c.get(ref, timeout=60) == 45
        print("THIN-DRIVER-OK")
    """))
    out = subprocess.run(
        [sys.executable, str(script)], capture_output=True, text=True,
        timeout=120,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""})
    assert "THIN-DRIVER-OK" in out.stdout, (out.stdout, out.stderr)
