"""Serve tests: deployments, handles, replicas, HTTP ingress, scaling."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve

HTTP_PORT = 18432


@pytest.fixture(scope="module")
def serve_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    serve.start(http_port=HTTP_PORT)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


def test_function_deployment_handle(serve_cluster):
    @serve.deployment
    def echo(payload):
        return {"got": payload}

    handle = serve.run(echo.bind(), route_prefix="/echo")
    out = handle.remote("hi").result(timeout=30)
    assert out == {"got": "hi"}


def test_class_deployment_methods_and_replicas(serve_cluster):
    @serve.deployment(num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.value = start

        def __call__(self, payload):
            return self.value

        def incr(self, by):
            self.value += by
            return self.value

    handle = serve.run(Counter.bind(10), route_prefix="/counter")
    assert handle.remote(None).result(timeout=30) == 10
    assert handle.incr.remote(5).result(timeout=30) == 15
    info = serve.status()["Counter"]
    assert info["num_replicas"] == 2


def test_http_ingress(serve_cluster):
    @serve.deployment
    def adder(req):
        return {"sum": req["json"]["a"] + req["json"]["b"]}

    serve.run(adder.bind(), route_prefix="/add")
    body = json.dumps({"a": 3, "b": 4}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{HTTP_PORT}/add", data=body,
        headers={"Content-Type": "application/json"})
    deadline = time.time() + 30
    last = None
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out == {"sum": 7}
            break
        except AssertionError:
            raise
        except Exception as e:
            last = e
            time.sleep(0.5)
    else:
        raise AssertionError(f"HTTP ingress never answered: {last}")

    # 404 for unknown route
    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{HTTP_PORT}/nothing", timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_redeploy_updates_code(serve_cluster):
    @serve.deployment(name="ver")
    def v1(req):
        return 1

    serve.run(v1.bind(), route_prefix="/ver")
    h = serve.get_deployment_handle("ver")
    assert h.remote(None).result(timeout=30) == 1

    @serve.deployment(name="ver")
    def v2(req):
        return 2

    serve.run(v2.bind(), route_prefix="/ver")
    deadline = time.time() + 30
    while time.time() < deadline:
        h = serve.get_deployment_handle("ver")
        if h.remote(None).result(timeout=30) == 2:
            break
        time.sleep(0.2)
    else:
        raise AssertionError("redeploy never took effect")


def test_delete_deployment(serve_cluster):
    @serve.deployment
    def gone(req):
        return "here"

    serve.run(gone.bind(), route_prefix="/gone")
    assert "gone" in serve.status()
    serve.delete("gone")
    assert "gone" not in serve.status()


def test_replica_failure_recovery(serve_cluster):
    @serve.deployment(name="fragile")
    class Fragile:
        def __call__(self, req):
            return "alive"

        def die(self, _):
            import os
            os._exit(1)

    handle = serve.run(Fragile.bind(), route_prefix="/fragile")
    assert handle.remote(None).result(timeout=30) == "alive"
    try:
        handle.die.remote(None).result(timeout=10)
    except Exception:
        pass
    # controller reconciles a fresh replica
    deadline = time.time() + 40
    errors = []
    while time.time() < deadline:
        try:
            h = serve.get_deployment_handle("fragile")
            if h.remote(None).result(timeout=10) == "alive":
                break
        except Exception as e:
            errors.append(f"{type(e).__name__}: {e}")
        time.sleep(0.5)
    else:
        import ray_tpu as _rt
        from ray_tpu.serve.controller import CONTROLLER_NAME

        ctrl = _rt.get_actor(CONTROLLER_NAME)
        nrep = len(_rt.get(ctrl.get_replicas.remote("fragile")))
        # Pull the newest worker stderr tails: if replacements are crash-
        # looping, the crash reason is in there.
        import glob
        import os as _os

        from ray_tpu._private import worker as worker_mod

        tails = []
        sess = worker_mod._global_cluster.session_dir
        errs = sorted(glob.glob(_os.path.join(sess, "logs", "*.err")),
                      key=_os.path.getmtime)[-4:]
        for f in errs:
            with open(f) as fh:
                tails.append(f"--- {_os.path.basename(f)} ---\n"
                             + fh.read()[-1500:])
        raise AssertionError(
            f"replica never recovered; replicas={nrep}, "
            f"last errors={errors[-3:]}\n" + "\n".join(tails))


def test_streaming_generator_through_handle(serve_cluster):
    """A deployment method returning a generator streams through
    ``remote_gen``: items arrive in order, lazily, and the stream is
    forgotten at exhaustion."""
    @serve.deployment(name="streamer")
    class Streamer:
        def counts(self, n):
            for i in range(n):
                yield {"i": i}

        async def acounts(self, n):
            for i in range(n):
                yield i * 10

    handle = serve.run(Streamer.bind(), http_port=None)
    items = list(handle.counts.remote_gen(4))
    assert items == [{"i": 0}, {"i": 1}, {"i": 2}, {"i": 3}]
    # Async generators ride the replica's persistent event loop.
    assert list(handle.acounts.remote_gen(3)) == [0, 10, 20]
    # Returning a generator through the non-streaming path is an error.
    with pytest.raises(Exception, match="remote_gen"):
        handle.counts.remote(2).result(timeout=30)
    serve.delete("streamer")


class _ReadyIter:
    """Iterator with the engine streams' non-blocking ``next_ready``
    probe: every item is already ready, so a batched ``stream_next``
    should pack up to ``max_items`` per RPC."""

    def __init__(self, items):
        self._items = list(items)

    def __iter__(self):
        return self

    def __next__(self):
        if not self._items:
            raise StopIteration
        return self._items.pop(0)

    def next_ready(self):
        if not self._items:
            raise StopIteration
        return self._items.pop(0)


def test_stream_next_batches_ready_items():
    """Replica-level batching parity at every chunk boundary: for
    stream lengths straddling the batch size (k-1, k, k+1, 2k, 2k+1),
    batched pulls return the identical item sequence, never more than
    ``max_items`` per reply, and the done flag rides with (or directly
    after) the trailing items — no lost tail, no phantom extra pull."""
    import cloudpickle

    from ray_tpu.serve.replica import Replica

    class Src:
        def stream(self, n):
            return _ReadyIter(range(n))

    rep = Replica(cloudpickle.dumps(Src), (), {}, "src", "r0")
    k = 8
    for n in (0, 1, k - 1, k, k + 1, 2 * k, 2 * k + 1):
        sid = rep.handle_request_stream("stream", (n,), {})
        got = []
        replies = 0
        while True:
            out = rep.stream_next(sid, max_items=k)
            replies += 1
            items = out.get("items", [])
            assert len(items) <= k
            got.extend(items)
            if out["done"]:
                break
            assert items, "no-progress reply on an all-ready stream"
        assert got == list(range(n)), f"n={n}"
        # All-ready items pack maximally: ceil(n/k) data replies plus
        # at most one trailing done-only reply.
        assert replies <= -(-n // k) + 1, f"n={n}: {replies} replies"
        assert rep.stats()["ongoing"] == 0

    # A probe that reports "nothing ready" (None) ends the batch early
    # without ending the stream.
    class Trickle:
        def __init__(self, items):
            self._items = list(items)

        def __next__(self):
            if not self._items:
                raise StopIteration
            return self._items.pop(0)

        def next_ready(self):
            return None

    rep2 = Replica(cloudpickle.dumps(lambda: Trickle([1, 2])), (), {},
                   "trickle", "r0")
    sid = rep2.handle_request_stream("__call__", (), {})
    assert rep2.stream_next(sid, max_items=k) == {
        "items": [1], "done": False}
    assert rep2.stream_next(sid, max_items=k) == {
        "items": [2], "done": False}
    assert rep2.stream_next(sid, max_items=k) == {"items": [], "done": True}


def test_remote_gen_batched_parity(serve_cluster):
    """End-to-end parity: the handle's batched ``remote_gen`` yields
    token-for-token the same sequence as a forced one-item-per-RPC
    pull, across lengths straddling the client batch size — the
    batching is a transport optimization, never a semantic change."""
    from ray_tpu.serve.handle import DeploymentResponseGenerator

    @serve.deployment(name="batcher")
    class Batcher:
        def ready(self, n):
            return _ReadyIter([{"i": i} for i in range(n)])

        def gen(self, n):
            for i in range(n):
                yield i * 3

    handle = serve.run(Batcher.bind(), http_port=None)
    k = DeploymentResponseGenerator._MAX_ITEMS
    try:
        for n in (0, 1, k - 1, k, k + 1, 2 * k + 1):
            want = [{"i": i} for i in range(n)]
            assert list(handle.ready.remote_gen(n)) == want, f"n={n}"
            # Forced legacy path: one item per RPC, same sequence.
            DeploymentResponseGenerator._MAX_ITEMS = 1
            try:
                assert list(handle.ready.remote_gen(n)) == want, f"n={n}"
            finally:
                DeploymentResponseGenerator._MAX_ITEMS = k
        # Plain generators (no next_ready probe) keep exact parity too.
        assert list(handle.gen.remote_gen(5)) == [0, 3, 6, 9, 12]
    finally:
        DeploymentResponseGenerator._MAX_ITEMS = k
        serve.delete("batcher")


def test_replica_persistent_event_loop(serve_cluster):
    """Async deployments share ONE event loop across requests (the old
    per-request ``asyncio.run`` gave every call a fresh loop, breaking
    any shared async state)."""
    @serve.deployment(name="looped")
    class Looped:
        def __init__(self):
            self.loop_ids = []

        async def __call__(self, _):
            import asyncio
            self.loop_ids.append(id(asyncio.get_running_loop()))
            return self.loop_ids

    handle = serve.run(Looped.bind(), http_port=None)
    for i in range(3):
        seen = handle.remote(i).result(timeout=30)
    assert len(seen) == 3 and len(set(seen)) == 1, seen
    serve.delete("looped")


def test_autoscaler_smoothing_ignores_single_spike():
    """One bursty queue-depth sample inside the look-back window must not
    change the target; a sustained load must (reference:
    autoscaling_policy.py:54-70 look-back averaging)."""
    from ray_tpu.serve.controller import _DeploymentState

    class _Ctl:
        """Borrow the real _autoscale_one logic on a fake controller."""

        def __init__(self):
            import threading

            self._lock = threading.Lock()

        from ray_tpu.serve.controller import ServeController

        _autoscale_one = ServeController._autoscale_one

    ac = {"min_replicas": 1, "max_replicas": 8,
          "target_ongoing_requests": 1.0,
          "upscale_delay_s": 0.0, "downscale_delay_s": 0.0,
          "look_back_period_s": 10.0}
    st = _DeploymentState({"num_replicas": 1, "autoscaling_config": ac},
                          b"", (), {})

    class _R:  # stand-in replica handles
        pass

    st.replicas = [_R()]
    st.target = 1
    ctl = _Ctl()

    # 5 idle samples then one spike of 8: the window average (~1.3) must
    # keep the target low.
    now = 1000.0
    for i in range(5):
        stats = {id(st.replicas[0]): {"ongoing": 0}}
        ctl._autoscale_one(st, stats, now + i)
    ctl._autoscale_one(st, {id(st.replicas[0]): {"ongoing": 8}}, now + 5)
    assert st.target <= 2, st.target

    # Sustained load fills the window: now it must scale up.
    for i in range(12):
        ctl._autoscale_one(st, {id(st.replicas[0]): {"ongoing": 8}},
                           now + 6 + i)
    assert st.target >= 4, st.target


def test_deployment_graph_composition(serve_cluster):
    """Multi-deployment app via nested .bind(): children deploy first and
    the parent receives live DeploymentHandles (reference:
    serve/deployment_graph_build.py)."""
    from ray_tpu import serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

    @serve.deployment
    class Adder:
        def __init__(self, inc):
            self.inc = inc

        def __call__(self, x):
            return x + self.inc

    @serve.deployment
    class Pipeline:
        def __init__(self, doubler, adder):
            self.doubler = doubler
            self.adder = adder

        def __call__(self, x):
            y = self.doubler.remote(x).result(timeout=30)
            return self.adder.remote(y).result(timeout=30)

    app = Pipeline.bind(Doubler.bind(), Adder.bind(10))
    handle = serve.run(app, http_port=None)
    assert handle.remote(5).result(timeout=60) == 20   # 5*2 + 10
    assert serve.status().keys() >= {"Pipeline", "Doubler", "Adder"}


def test_replica_death_detected_via_actor_events(serve_cluster):
    """Killing a replica actor: the controller learns via the GCS
    actor-state channel and replaces it promptly (not after 30 probe
    misses), and handles see the new replica set via long-poll push."""
    import time as _t

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), http_port=None)
    assert handle.remote(1).result(timeout=30) == 1
    from ray_tpu.serve.api import _controller
    ctrl = _controller()
    replicas = ray_tpu.get(ctrl.get_replicas.remote("Echo"))
    assert len(replicas) == 2
    ray_tpu.kill(replicas[0])
    # Replacement should land well inside the probe-miss budget (~6s+).
    deadline = _t.time() + 15
    while _t.time() < deadline:
        current = ray_tpu.get(ctrl.get_replicas.remote("Echo"))
        live = [r for r in current if r is not replicas[0]]
        if len(current) == 2 and replicas[0] not in current:
            break
        _t.sleep(0.3)
    current = ray_tpu.get(ctrl.get_replicas.remote("Echo"))
    assert len(current) == 2 and replicas[0] not in current
    assert handle.remote(7).result(timeout=30) == 7


def test_serve_rest_config_deploy(serve_cluster, tmp_path, monkeypatch):
    """Declarative REST deploy (reference: dashboard/modules/serve/ +
    serve/schema.py): PUT a config with an import_path, GET status."""
    import json as _json
    import sys
    import urllib.request

    from ray_tpu.dashboard import start_dashboard

    mod_dir = tmp_path / "serve_rest_mod"
    mod_dir.mkdir()
    (mod_dir / "my_rest_app.py").write_text(
        "from ray_tpu import serve\n"
        "@serve.deployment\n"
        "class Greeter:\n"
        "    def __call__(self, name):\n"
        "        return f'hi {name}'\n"
        "app = Greeter.bind()\n")
    monkeypatch.syspath_prepend(str(mod_dir))
    sys.modules.pop("my_rest_app", None)

    try:
        _a, port = start_dashboard(port=18267)
    except Exception:
        port = 18265
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/serve/applications",
        data=_json.dumps({"applications": [{
            "name": "Greeter", "import_path": "my_rest_app:app",
            "http_port": None}]}).encode(),
        method="PUT", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        out = _json.loads(r.read())
    assert out == {"deployed": ["Greeter"]}

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/serve/applications",
            timeout=30) as r:
        status = _json.loads(r.read())
    assert "Greeter" in status["applications"]

    from ray_tpu import serve
    h = serve.get_deployment_handle("Greeter")
    assert h.remote("rest").result(timeout=60) == "hi rest"


def test_http_ingress_routes_graph_root(serve_cluster):
    """A deployment-graph root is HTTP-reachable through the proxy at
    its route_prefix like any deployment (reference: http_proxy routing
    + deployment graph ingress)."""
    import json as _json
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment
    class Upper:
        def __call__(self, s):
            return str(s).upper()

    @serve.deployment
    class Greet:
        def __init__(self, upper):
            self.upper = upper

        def __call__(self, payload):
            # HTTP proxy contract: {"path", "query", "method", "json"}.
            name = (payload.get("json") or {}).get("name", "world") \
                if isinstance(payload, dict) else payload
            return {"greeting": self.upper.remote(
                f"hi {name}").result(timeout=30)}

    serve.run(Greet.bind(Upper.bind()), route_prefix="/greet")
    from ray_tpu.serve.api import _controller
    port = ray_tpu.get(_controller().proxy_port.remote())
    assert port is not None   # controller's proxy (started by the module)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/greet",
        data=_json.dumps({"name": "graph"}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        out = _json.loads(r.read())
    assert out == {"greeting": "HI GRAPH"}
