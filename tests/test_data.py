"""Data library tests: transforms, fusion, all-to-all ops, IO, groupby."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_range_and_transforms(ray_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    out = ds.map(lambda x: x * 2).filter(lambda x: x % 10 == 0).take_all()
    assert out == [x * 2 for x in range(100) if (x * 2) % 10 == 0]


def test_flat_map_and_fusion(ray_cluster):
    ds = rd.range(10, parallelism=2).flat_map(lambda x: [x, x])
    assert ds.count() == 20
    # chained stages fuse into one task per block: still 2 input blocks
    ds2 = ds.map(lambda x: x + 1).filter(lambda x: x % 2 == 0)
    assert len(ds2._execute()) == 2


def test_map_batches_numpy(ray_cluster):
    ds = rd.from_items([{"x": i, "y": i * 2} for i in range(32)],
                       parallelism=4)

    def double(batch):
        return {"x": batch["x"] * 2, "y": batch["y"]}

    out = ds.map_batches(double, batch_size=8).take_all()
    assert out[3] == {"x": 6, "y": 6}


def test_iter_batches_formats(ray_cluster):
    ds = rd.from_items([{"a": i} for i in range(10)], parallelism=3)
    batches = list(ds.iter_batches(batch_size=4, batch_format="numpy"))
    assert [len(b["a"]) for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(batches[0]["a"], [0, 1, 2, 3])
    dfs = list(ds.iter_batches(batch_size=5, batch_format="pandas"))
    assert len(dfs) == 2 and list(dfs[0]["a"]) == [0, 1, 2, 3, 4]


def test_repartition_shuffle_sort(ray_cluster):
    ds = rd.range(20, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert sorted(ds.take_all()) == list(range(20))

    sh = rd.range(50, parallelism=2).random_shuffle(seed=0)
    assert sorted(sh.take_all()) == list(range(50))
    assert sh.take_all() != list(range(50))

    srt = rd.from_items([{"k": i % 7, "v": i} for i in range(21)],
                        parallelism=3).sort("k", descending=True)
    ks = [r["k"] for r in srt.take_all()]
    assert ks == sorted(ks, reverse=True)


def test_zip_union_split(ray_cluster):
    a = rd.from_items([{"a": i} for i in range(6)])
    b = rd.from_items([{"b": i * 10} for i in range(6)])
    z = a.zip(b).take_all()
    assert z[2] == {"a": 2, "b": 20}

    u = rd.range(5).union(rd.range(3))
    assert u.count() == 8

    parts = rd.range(10).split(2)
    assert [p.count() for p in parts] == [5, 5]


def test_groupby(ray_cluster):
    ds = rd.from_items([{"k": i % 3, "v": float(i)} for i in range(12)],
                       parallelism=4)
    counts = ds.groupby("k").count().take_all()
    assert counts == [{"k": 0, "count": 4}, {"k": 1, "count": 4},
                      {"k": 2, "count": 4}]
    sums = ds.groupby("k").sum("v").take_all()
    assert sums[0]["sum(v)"] == sum(float(i) for i in range(12) if i % 3 == 0)


def test_aggregates_and_schema(ray_cluster):
    ds = rd.from_items([{"x": i} for i in range(10)])
    assert ds.sum("x") == 45
    assert ds.min("x") == 0
    assert ds.max("x") == 9
    assert ds.mean("x") == 4.5
    assert ds.schema() == {"x": "int"}


def test_read_write_roundtrip(ray_cluster, tmp_path):
    ds = rd.from_items([{"a": i, "b": f"s{i}"} for i in range(10)],
                       parallelism=2)
    ds.write_json(str(tmp_path / "j"))
    back = rd.read_json(str(tmp_path / "j"))
    assert sorted(back.take_all(), key=lambda r: r["a"]) == ds.take_all()

    ds.write_parquet(str(tmp_path / "p"))
    back2 = rd.read_parquet(str(tmp_path / "p"))
    assert back2.count() == 10

    (tmp_path / "t.txt").write_text("hello\nworld\n")
    assert rd.read_text(str(tmp_path / "t.txt")).take_all() == [
        {"text": "hello"}, {"text": "world"}]


def test_from_numpy_pandas_arrow(ray_cluster):
    arr = np.arange(12).reshape(4, 3)
    ds = rd.from_numpy(arr)
    np.testing.assert_array_equal(ds.take(1)[0]["data"], [0, 1, 2])

    import pandas as pd
    df = pd.DataFrame({"x": [1, 2], "y": ["a", "b"]})
    assert rd.from_pandas(df).take_all() == [
        {"x": 1, "y": "a"}, {"x": 2, "y": "b"}]

    import pyarrow as pa
    t = pa.table({"q": [7, 8]})
    assert rd.from_arrow(t).count() == 2


def test_logical_plan_explain_and_rules(ray_cluster):
    """Logical operator layer (reference: data/_internal/logical/):
    named operators, projection collapse, limit pushdown, fusion in the
    rendered physical plan."""
    ds = (rd.range(100, parallelism=4)
          .map(lambda x: {"a": x, "b": -x, "c": 2 * x})
          .select_columns(["a", "b", "c"])
          .select_columns(["a", "b"])
          .limit(5))
    text = ds.explain()
    assert "Limit[5]" in text and "SelectColumns" in text
    # Projection collapse: one SelectColumns survives optimization.
    opt_line = [ln for ln in text.splitlines() if ln.startswith("Optimized")][0]
    assert opt_line.count("SelectColumns") == 1
    # Limit pushed in front of the row-preserving chain -> EarlyStop.
    assert "EarlyStop[5]" in text
    rows = ds.take_all()
    assert rows == [{"a": a, "b": -a} for a in range(5)]

    # Filter blocks the push (it shrinks rows): limit must apply to the
    # FILTERED stream, exactly.
    ds2 = (rd.range(100, parallelism=4)
           .map(lambda x: {"a": x, "b": -x})
           .filter(lambda r: r["a"] % 2 == 0)
           .limit(5))
    t2 = ds2.explain()
    assert "EarlyStop" not in t2 and "GlobalTrim[5]" in t2
    assert ds2.take_all() == [{"a": a, "b": -a} for a in (0, 2, 4, 6, 8)]


def test_limit_pushdown_skips_blocks(ray_cluster):
    """A pushed-down limit must not execute every block: with 8 blocks
    and limit(3), at most 2 block tasks run (execution is sequential
    until the limit fills)."""
    ds = rd.range(80, parallelism=8).map(lambda x: x + 1).limit(3)
    blocks = ds._execute()
    assert len(blocks) <= 2, len(blocks)
    assert sorted(ds.take_all()) == [1, 2, 3]


def test_leading_limit_caps_input_not_output(ray_cluster):
    """limit() BEFORE other ops bounds what the chain CONSUMES: the
    filter sees only the first 5 rows (none >= 10 -> empty), and a
    flat_map after a limit still doubles the capped input."""
    ds = rd.range(100, parallelism=1).limit(5).filter(lambda x: x >= 10)
    assert ds.take_all() == []
    ds2 = rd.range(100, parallelism=2).limit(5).flat_map(
        lambda x: [x, x])
    assert sorted(ds2.take_all()) == [0, 0, 1, 1, 2, 2, 3, 3, 4, 4]
    # Streaming paths honor limits too.
    ds3 = rd.range(100, parallelism=4).map(lambda x: x).limit(5)
    assert list(ds3.iter_rows()) == [0, 1, 2, 3, 4]
    # Limit is GLOBAL across streaming_split shards (reference
    # semantics): 2 shards of limit(6) return 6 rows total, not 12.
    shards = rd.range(40, parallelism=4).limit(6).streaming_split(2)
    total = sum(len(sh.take_all()) for sh in shards)
    assert total == 6, total
    # ...and across pipeline windows.
    pipe = rd.range(40, parallelism=4).limit(6).window(blocks_per_window=2)
    assert sum(1 for _ in pipe.iter_rows()) == 6


def test_limit_blocked_by_flat_map(ray_cluster):
    """flat_map can EXPAND rows, so a limit after it must NOT push past
    it (correctness of the pushdown guard)."""
    ds = (rd.range(10, parallelism=2)
          .flat_map(lambda x: [x, x])
          .limit(4))
    text = ds.explain()
    assert "EarlyStop" not in text          # stayed behind FlatMap
    assert len(ds.take_all()) == 4
