"""KV-cache generation tests: cached forward == full forward, greedy
determinism, sampling shapes, and the slotted-batch programs behind the
continuous batching engine (prefill_slot / adopt_slot / decode_step)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import GPTConfig, forward, init_params
from ray_tpu.models.generate import (
    _forward_cached, adopt_slot, decode_step, generate, init_cache,
    init_slotted_cache, prefill, prefill_slot,
)


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_cached_forward_matches_full(setup):
    cfg, params = setup
    toks = jax.random.randint(jax.random.key(1), (2, 24), 0,
                              cfg.vocab_size)
    full = forward(params, toks, cfg)

    # prefill 16, then decode 8 tokens one at a time
    cache = init_cache(cfg, 2, 24)
    logits_p, cache = _forward_cached(params, toks[:, :16], cache, cfg)
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full[:, :16]), atol=1e-4)
    for i in range(16, 24):
        step_logits, cache = _forward_cached(
            params, toks[:, i:i + 1], cache, cfg)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, i]), atol=1e-4)


def test_cached_forward_rotary(setup):
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, rotary=True)
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (1, 16), 0,
                              cfg.vocab_size)
    full = forward(params, toks, cfg)
    cache = init_cache(cfg, 1, 16)
    logits_c, cache = _forward_cached(params, toks[:, :12], cache, cfg)
    for i in range(12, 16):
        sl, cache = _forward_cached(params, toks[:, i:i + 1], cache, cfg)
    np.testing.assert_allclose(np.asarray(sl[:, 0]),
                               np.asarray(full[:, -1]), atol=1e-4)


def test_greedy_generation_matches_argmax_rollout(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(2), (1, 8), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, jax.random.key(0), cfg=cfg,
                   max_new_tokens=6, temperature=0.0)
    assert out.shape == (1, 6)

    # naive rollout with the non-cached forward
    seq = prompt
    naive = []
    for _ in range(6):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        naive.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(x) for x in out[0]] == naive


def test_sampled_generation_shapes_and_validity(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(3), (3, 5), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, jax.random.key(7), cfg=cfg,
                   max_new_tokens=10, temperature=0.8, top_k=20)
    assert out.shape == (3, 10)
    assert ((np.asarray(out) >= 0) &
            (np.asarray(out) < cfg.vocab_size)).all()
    # deterministic given the same key
    out2 = generate(params, prompt, jax.random.key(7), cfg=cfg,
                    max_new_tokens=10, temperature=0.8, top_k=20)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


# ------------------------------------------------- slotted batch programs


def _run_slotted(cfg, params, jobs, *, slots=4, max_len=64, bucket=16,
                 n=6, temperature=0.0, top_k=0):
    """Drive the slotted programs by hand: ``jobs`` maps slot -> (prompt,
    seed, join_step); a request joins the in-flight batch at its
    join_step and leaves when it has n tokens. Returns slot -> tokens."""
    cache = init_slotted_cache(cfg, slots, max_len)
    last = jnp.zeros((slots,), jnp.int32)
    active = jnp.zeros((slots,), bool)
    seeds = jnp.zeros((slots,), jnp.int32)
    out = {s: [] for s in jobs}
    max_join = max(j[2] for j in jobs.values())
    step = 0
    while any(len(out[s]) < n for s in jobs) or step <= max_join:
        for s, (prompt, seed, join) in jobs.items():
            if join == step:
                padded = jnp.zeros((1, bucket), jnp.int32
                                   ).at[:, :len(prompt)].set(
                    jnp.asarray(prompt, jnp.int32))
                first, kv = prefill_slot(
                    params, padded, jnp.int32(len(prompt)),
                    jnp.int32(seed), cfg=cfg, temperature=temperature,
                    top_k=top_k)
                cache = adopt_slot(cache, jnp.int32(s), kv,
                                   jnp.int32(len(prompt)))
                last = last.at[s].set(first[0])
                active = active.at[s].set(True)
                seeds = seeds.at[s].set(seed)
                out[s].append(int(first[0]))
        if active.any():
            nxt, cache = decode_step(
                params, cache, last, active, seeds, cfg=cfg,
                temperature=temperature, top_k=top_k)
            for s in jobs:
                if bool(active[s]):
                    out[s].append(int(nxt[s]))
                    if len(out[s]) >= n:
                        active = active.at[s].set(False)
            last = jnp.where(active, nxt, last)
        step += 1
        assert step < 10 * n + 10, "slotted rollout never converged"
    return out


@pytest.mark.parametrize("rotary", [False, True])
def test_slotted_prefill_decode_matches_generate(rotary):
    """Incremental prefill_slot + N x decode_step reproduces generate()
    token-for-token (greedy): same math through the padded bucket, the
    per-slot cache splice, and the per-slot length masks."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, rotary=rotary)
    params = init_params(jax.random.key(0), cfg)
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(5), (9,), 0, cfg.vocab_size)]
    n = 7
    ref = [int(x) for x in generate(
        params, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        cfg=cfg, max_new_tokens=n, temperature=0.0)[0]]
    out = _run_slotted(cfg, params, {2: (prompt, 0, 0)}, n=n)
    assert out[2] == ref


def test_slotted_join_leave_does_not_perturb_other_slots():
    """Requests joining/leaving the in-flight batch mid-decode must not
    change any other slot's tokens. Run SAMPLED (temperature > 0) so any
    cross-slot leak — cache splices, length masks, or sampling keys —
    changes the sequence."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, rotary=True)
    params = init_params(jax.random.key(0), cfg)
    pa, pb, pc = [5, 9, 2], [7, 7, 7, 7, 1], [3, 1]
    kw = dict(n=8, temperature=0.9, top_k=12)

    alone = _run_slotted(cfg, params, {1: (pa, 42, 0)}, **kw)
    # B joins 3 steps into A's decode; C joins as B is retiring.
    crowd = _run_slotted(cfg, params, {
        1: (pa, 42, 0), 0: (pb, 7, 3), 3: (pc, 99, 6)}, **kw)
    assert crowd[1] == alone[1]
    # ... and the joiners themselves are batch-composition independent.
    b_alone = _run_slotted(cfg, params, {0: (pb, 7, 0)}, **kw)
    assert crowd[0] == b_alone[0]


def test_slotted_sampling_tracks_request_seed():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, rotary=True)
    params = init_params(jax.random.key(0), cfg)
    kw = dict(n=6, temperature=0.9, top_k=16)
    a = _run_slotted(cfg, params, {0: ([4, 4, 4], 1, 0)}, **kw)
    b = _run_slotted(cfg, params, {0: ([4, 4, 4], 2, 0)}, **kw)
    c = _run_slotted(cfg, params, {0: ([4, 4, 4], 1, 0)}, **kw)
    assert a[0] == c[0]          # deterministic per seed
    assert a[0] != b[0]          # seed actually steers sampling


def test_prefill_last_logits(setup):
    cfg, params = setup
    toks = jax.random.randint(jax.random.key(4), (2, 12), 0,
                              cfg.vocab_size)
    last, cache = prefill(params, toks, cfg, max_len=32)
    full = forward(params, toks, cfg)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               atol=1e-4)
    assert int(cache["length"]) == 12
