"""KV-cache generation tests: cached forward == full forward, greedy
determinism, sampling shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import GPTConfig, forward, init_params
from ray_tpu.models.generate import (
    _forward_cached, generate, init_cache, prefill,
)


@pytest.fixture(scope="module")
def setup():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def test_cached_forward_matches_full(setup):
    cfg, params = setup
    toks = jax.random.randint(jax.random.key(1), (2, 24), 0,
                              cfg.vocab_size)
    full = forward(params, toks, cfg)

    # prefill 16, then decode 8 tokens one at a time
    cache = init_cache(cfg, 2, 24)
    logits_p, cache = _forward_cached(params, toks[:, :16], cache, cfg)
    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(full[:, :16]), atol=1e-4)
    for i in range(16, 24):
        step_logits, cache = _forward_cached(
            params, toks[:, i:i + 1], cache, cfg)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(full[:, i]), atol=1e-4)


def test_cached_forward_rotary(setup):
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, rotary=True)
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (1, 16), 0,
                              cfg.vocab_size)
    full = forward(params, toks, cfg)
    cache = init_cache(cfg, 1, 16)
    logits_c, cache = _forward_cached(params, toks[:, :12], cache, cfg)
    for i in range(12, 16):
        sl, cache = _forward_cached(params, toks[:, i:i + 1], cache, cfg)
    np.testing.assert_allclose(np.asarray(sl[:, 0]),
                               np.asarray(full[:, -1]), atol=1e-4)


def test_greedy_generation_matches_argmax_rollout(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(2), (1, 8), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, jax.random.key(0), cfg=cfg,
                   max_new_tokens=6, temperature=0.0)
    assert out.shape == (1, 6)

    # naive rollout with the non-cached forward
    seq = prompt
    naive = []
    for _ in range(6):
        logits = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        naive.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(x) for x in out[0]] == naive


def test_sampled_generation_shapes_and_validity(setup):
    cfg, params = setup
    prompt = jax.random.randint(jax.random.key(3), (3, 5), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, jax.random.key(7), cfg=cfg,
                   max_new_tokens=10, temperature=0.8, top_k=20)
    assert out.shape == (3, 10)
    assert ((np.asarray(out) >= 0) &
            (np.asarray(out) < cfg.vocab_size)).all()
    # deterministic given the same key
    out2 = generate(params, prompt, jax.random.key(7), cfg=cfg,
                    max_new_tokens=10, temperature=0.8, top_k=20)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_prefill_last_logits(setup):
    cfg, params = setup
    toks = jax.random.randint(jax.random.key(4), (2, 12), 0,
                              cfg.vocab_size)
    last, cache = prefill(params, toks, cfg, max_len=32)
    full = forward(params, toks, cfg)
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               atol=1e-4)
    assert int(cache["length"]) == 12
