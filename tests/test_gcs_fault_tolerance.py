"""GCS fault tolerance: persistence + restart recovery + health checks.

Reference parity targets: redis_store_client.h:28 (durable GCS tables),
GcsInitData restore at server start, raylet re-registration after GCS
failover, and gcs_health_check_manager.h:39 (active liveness checks).

Two tiers: the in-process ``GcsServer`` with ``crash_for_test`` (fast;
most cases), and the REAL out-of-process GCS subprocess
(``gcs_launcher.GcsProcess``) SIGKILLed mid-workload — the topology
``ray_tpu start --head`` actually deploys.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import lockdep, protocol
from ray_tpu._private.config import config
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.gcs_launcher import GcsProcess
from ray_tpu._private.node_manager import NodeManager


def _wait_until(pred, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def external_cluster(tmp_path):
    """GCS with durable storage + one NodeManager, driver attached by
    address (so ray_tpu.shutdown() doesn't own the control plane)."""
    storage = str(tmp_path / "gcs.db")
    gcs = GcsServer(storage_path=storage)
    nm = NodeManager(
        gcs_address=gcs.address,
        session_dir=str(tmp_path / "session"),
        num_cpus=2, num_tpus=0, resources=None,
        object_store_memory=64 * 1024 * 1024,
        is_head=True, node_name="head")
    ray_tpu.init(address=gcs.address)
    state = {"gcs": gcs, "nm": nm, "storage": storage}
    yield state
    ray_tpu.shutdown()
    try:
        state["nm"].shutdown()
    except Exception:
        pass
    try:
        state["gcs"].close()
    except Exception:
        pass


class _Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n


def test_gcs_crash_restart_preserves_actor_and_kv(external_cluster):
    """kill -9 the head GCS mid-run with a detached actor alive; restart
    on the same port with the same storage; the driver reconnects, the
    node rejoins, and the SAME actor process answers with its state."""
    st = external_cluster
    from ray_tpu._private import worker as worker_mod

    cls = ray_tpu.remote(_Counter)
    c = cls.options(name="ctr", lifetime="detached").remote()
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 1

    kv = worker_mod.require_worker().kv()
    kv.put(b"survives", b"yes")

    port = int(st["gcs"].address.rsplit(":", 1)[1])
    st["gcs"].crash_for_test()

    # Restart the head on the same port with the same durable storage.
    st["gcs"] = GcsServer(port=port, storage_path=st["storage"])

    # The node manager rejoins on its own and re-reports the live actor.
    _wait_until(
        lambda: any(n["Alive"]
                    for n in worker_mod.require_worker().nodes()),
        msg="node rejoined restarted gcs")

    # KV table restored from storage.
    assert kv.get(b"survives") == b"yes"

    # Named-actor directory restored; the handle routes to the SAME
    # process (state 1 -> 2, not a restarted 0 -> 1).
    h = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(h.incr.remote(), timeout=30) == 2
    # The original handle works too.
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 3


def test_gcs_restart_task_submission_works(external_cluster):
    """Plain tasks submit and run after a head restart (function store
    restored from persistence)."""
    st = external_cluster

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=30) == 3

    port = int(st["gcs"].address.rsplit(":", 1)[1])
    st["gcs"].crash_for_test()
    st["gcs"] = GcsServer(port=port, storage_path=st["storage"])

    from ray_tpu._private import worker as worker_mod

    _wait_until(
        lambda: any(n["Alive"]
                    for n in worker_mod.require_worker().nodes()),
        msg="node rejoined restarted gcs")
    assert ray_tpu.get(add.remote(40, 2), timeout=30) == 42


# ------------------------------------------- real out-of-process GCS


@pytest.fixture
def subprocess_cluster(tmp_path):
    """The REAL split topology: GCS as its own subprocess (own
    interpreter/GIL) with durable storage, one NodeManager and the
    driver attached purely by address."""
    storage = str(tmp_path / "gcs.db")
    session = str(tmp_path / "session")
    gcs_proc = GcsProcess(session_dir=session, storage_path=storage)
    nm = NodeManager(
        gcs_address=gcs_proc.address,
        session_dir=session,
        num_cpus=2, num_tpus=0, resources=None,
        object_store_memory=64 * 1024 * 1024,
        is_head=True, node_name="head")
    ray_tpu.init(address=gcs_proc.address)
    state = {"gcs_proc": gcs_proc, "nm": nm, "storage": storage,
             "session": session}
    yield state
    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    try:
        state["nm"].shutdown()
    except Exception:
        pass
    try:
        state["gcs_proc"].terminate()
    except Exception:
        pass


class _SlowCounter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n

    def slow(self, delay):
        time.sleep(delay)
        return "done"


def test_gcs_subprocess_sigkill_mid_workload_recovers(subprocess_cluster):
    """SIGKILL the real GCS process with an actor-task ray.get in
    flight; restart it on the same port from the same gcs_storage. The
    NM redials and re-registers, the driver channel redials on its next
    call, the in-flight get COMPLETES, and durable state (KV, detached
    named actor — same process, not a restarted one) survives."""
    st = subprocess_cluster
    from ray_tpu._private import worker as worker_mod

    cls = ray_tpu.remote(_SlowCounter)
    c = cls.options(name="ctr", lifetime="detached").remote()
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 1
    kv = worker_mod.require_worker().kv()
    kv.put(b"survives", b"yes")

    # In-flight get across the kill: the actor task takes ~4s; the GCS
    # dies ~0.5s in and comes back ~2s in.
    ref = c.slow.remote(4.0)
    result = {}

    def bg_get():
        t0 = time.time()
        try:
            result["value"] = ray_tpu.get(ref, timeout=90)
        except BaseException as e:  # surfaced to the assert below
            result["error"] = e
        result["elapsed"] = time.time() - t0

    th = threading.Thread(target=bg_get)
    th.start()
    time.sleep(0.5)

    port = int(st["gcs_proc"].address.rsplit(":", 1)[1])
    os.kill(st["gcs_proc"].pid, signal.SIGKILL)
    st["gcs_proc"].proc.wait(timeout=30)
    time.sleep(1.0)
    st["gcs_proc"] = GcsProcess(session_dir=st["session"], port=port,
                                storage_path=st["storage"])

    # NM redial + re-registration against the restarted process.
    _wait_until(
        lambda: any(n["Alive"]
                    for n in worker_mod.require_worker().nodes()),
        msg="node rejoined restarted gcs subprocess")

    # The in-flight get completed (bounded by its own timeout, which it
    # must come in far under). The wall budget is load-aware: on a
    # single-core box the redial/re-registration storm timeshares with
    # the 4s actor task itself, so the same recovery legitimately takes
    # longer than on a multi-core runner.
    budget = 60 if (os.cpu_count() or 1) >= 2 else 85
    th.join(timeout=budget + 25)
    assert not th.is_alive(), "in-flight get hung across the GCS kill"
    assert result.get("value") == "done", result.get("error")
    assert result["elapsed"] < budget

    # Durable state recovered from gcs_storage.
    assert kv.get(b"survives") == b"yes"
    h = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(h.incr.remote(), timeout=30) == 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(40, 2), timeout=30) == 42


def test_gcs_subprocess_dead_typed_error_within_rpc_timeout(
        subprocess_cluster):
    """GCS SIGKILLed and NOT restarted: control RPCs and in-flight gets
    fail with a typed error within ~gcs_rpc_timeout_s — never a hang."""
    st = subprocess_cluster
    from ray_tpu import exceptions
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.worker import ObjectRef

    w = worker_mod.require_worker()
    assert w.kv().put(b"a", b"b")
    old_timeout = config.gcs_rpc_timeout_s
    config.set("gcs_rpc_timeout_s", 5.0)
    try:
        st["gcs_proc"].kill()
        typed = (ConnectionError, protocol.ConnectionClosed, OSError,
                 TimeoutError, exceptions.GetTimeoutError)

        t0 = time.time()
        with pytest.raises(typed):
            w.kv().get(b"a")
        assert time.time() - t0 < 3 * 5.0

        # An in-flight get of an object the dead GCS would have to
        # resolve: typed failure, bounded.
        ref = ObjectRef(ObjectID.from_random())
        t0 = time.time()
        with pytest.raises(typed):
            ray_tpu.get(ref, timeout=3)
        assert time.time() - t0 < 3 * 5.0
    finally:
        config.set("gcs_rpc_timeout_s", old_timeout)


# ------------------------------- lockdep over the bootstrap/serve loop


def test_blocking_region_guard_detects_held_lock():
    """The runtime guard the launcher plants before child-process waits:
    entering a blocking region while holding a tracked lock is recorded
    as a violation."""
    lk = lockdep.tracked(key="test_gcs_ft:guard-probe")
    with lk:
        lockdep.note_blocking_region("probe")
    found = lockdep.take_violations()
    assert len(found) == 1
    assert "blocking:probe" in str(found[0])
    assert "guard-probe" in str(found[0])


def test_gcs_bootstrap_shutdown_takes_no_shard_lock(tmp_path):
    """Regression fixture for the split: spawn the GCS entrypoint with
    lockdep enabled IN THE CHILD (shipped via the config diff), drive
    its serve loop, and tear it down gracefully. The parent-side
    bootstrap/terminate waits run under the note_blocking_region guard
    (the module-level autouse fixture asserts no violation), and the
    child asserts its own serve/shutdown path witnessed no lock-order
    cycle — a violated child exits rc=3, so rc==0 IS the assertion."""
    old = config.lockdep_enabled
    config.set("lockdep_enabled", True)
    try:
        gcs_proc = GcsProcess(session_dir=str(tmp_path / "session"))
        conn = protocol.connect(gcs_proc.address, name="lockdep-probe",
                                timeout=10)
        try:
            assert conn.request("kv_put", {
                "ns": "", "key": b"k", "value": b"v"}, timeout=10)
            assert conn.request("kv_get", {"ns": "", "key": b"k"},
                                timeout=10) == b"v"
            stats = conn.request("control_plane_stats", timeout=10)
            assert stats["gcs_process"]["out_of_process"] is True
            assert stats["gcs_process"]["pid"] == gcs_proc.pid
        finally:
            conn.close()
        rc = gcs_proc.terminate(timeout=30)
        assert rc == 0, (
            f"gcs child exited rc={rc}: lockdep witnessed a violation "
            f"in the serve/shutdown path (rc=3) or the drain failed")
    finally:
        config.set("lockdep_enabled", old)


def test_health_check_marks_wedged_node_dead(tmp_path):
    """A node that stops heartbeating (but keeps its socket open) is
    declared dead by the GCS health checker."""
    from ray_tpu._private.config import config

    old_period = config.raylet_heartbeat_period_ms
    old_hc = config.health_check_period_ms
    old_thresh = config.health_check_failure_threshold
    config.set("raylet_heartbeat_period_ms", 100)
    config.set("health_check_period_ms", 100)
    config.set("health_check_failure_threshold", 5)
    try:
        gcs = GcsServer()
        nm = NodeManager(
            gcs_address=gcs.address,
            session_dir=str(tmp_path / "session"),
            num_cpus=1, num_tpus=0, resources=None,
            object_store_memory=32 * 1024 * 1024,
            is_head=True, node_name="head")
        _wait_until(lambda: any(n.alive for n in gcs._nodes.values()),
                    msg="node registered")
        # Wedge: stop the heartbeat loop without closing the socket.
        nm._shutdown = True  # heartbeat/reap loops exit; conn stays open
        _wait_until(
            lambda: all(not n.alive for n in gcs._nodes.values()),
            timeout=30,
            msg="gcs declared the silent node dead")
    finally:
        config.set("raylet_heartbeat_period_ms", old_period)
        config.set("health_check_period_ms", old_hc)
        config.set("health_check_failure_threshold", old_thresh)
        try:
            nm._shutdown = False
            nm.shutdown()
        except Exception:
            pass
        gcs.close()
