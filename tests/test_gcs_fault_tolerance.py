"""GCS fault tolerance: persistence + restart recovery + health checks.

Reference parity targets: redis_store_client.h:28 (durable GCS tables),
GcsInitData restore at server start, raylet re-registration after GCS
failover, and gcs_health_check_manager.h:39 (active liveness checks).
"""

import time

import pytest

import ray_tpu
from ray_tpu._private.gcs import GcsServer
from ray_tpu._private.node_manager import NodeManager


def _wait_until(pred, timeout=30.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def external_cluster(tmp_path):
    """GCS with durable storage + one NodeManager, driver attached by
    address (so ray_tpu.shutdown() doesn't own the control plane)."""
    storage = str(tmp_path / "gcs.db")
    gcs = GcsServer(storage_path=storage)
    nm = NodeManager(
        gcs_address=gcs.address,
        session_dir=str(tmp_path / "session"),
        num_cpus=2, num_tpus=0, resources=None,
        object_store_memory=64 * 1024 * 1024,
        is_head=True, node_name="head")
    ray_tpu.init(address=gcs.address)
    state = {"gcs": gcs, "nm": nm, "storage": storage}
    yield state
    ray_tpu.shutdown()
    try:
        state["nm"].shutdown()
    except Exception:
        pass
    try:
        state["gcs"].close()
    except Exception:
        pass


class _Counter:
    def __init__(self):
        self.n = 0

    def incr(self):
        self.n += 1
        return self.n


def test_gcs_crash_restart_preserves_actor_and_kv(external_cluster):
    """kill -9 the head GCS mid-run with a detached actor alive; restart
    on the same port with the same storage; the driver reconnects, the
    node rejoins, and the SAME actor process answers with its state."""
    st = external_cluster
    from ray_tpu._private import worker as worker_mod

    cls = ray_tpu.remote(_Counter)
    c = cls.options(name="ctr", lifetime="detached").remote()
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 1

    kv = worker_mod.require_worker().kv()
    kv.put(b"survives", b"yes")

    port = int(st["gcs"].address.rsplit(":", 1)[1])
    st["gcs"].crash_for_test()

    # Restart the head on the same port with the same durable storage.
    st["gcs"] = GcsServer(port=port, storage_path=st["storage"])

    # The node manager rejoins on its own and re-reports the live actor.
    _wait_until(
        lambda: any(n["Alive"]
                    for n in worker_mod.require_worker().nodes()),
        msg="node rejoined restarted gcs")

    # KV table restored from storage.
    assert kv.get(b"survives") == b"yes"

    # Named-actor directory restored; the handle routes to the SAME
    # process (state 1 -> 2, not a restarted 0 -> 1).
    h = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(h.incr.remote(), timeout=30) == 2
    # The original handle works too.
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 3


def test_gcs_restart_task_submission_works(external_cluster):
    """Plain tasks submit and run after a head restart (function store
    restored from persistence)."""
    st = external_cluster

    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=30) == 3

    port = int(st["gcs"].address.rsplit(":", 1)[1])
    st["gcs"].crash_for_test()
    st["gcs"] = GcsServer(port=port, storage_path=st["storage"])

    from ray_tpu._private import worker as worker_mod

    _wait_until(
        lambda: any(n["Alive"]
                    for n in worker_mod.require_worker().nodes()),
        msg="node rejoined restarted gcs")
    assert ray_tpu.get(add.remote(40, 2), timeout=30) == 42


def test_health_check_marks_wedged_node_dead(tmp_path):
    """A node that stops heartbeating (but keeps its socket open) is
    declared dead by the GCS health checker."""
    from ray_tpu._private.config import config

    old_period = config.raylet_heartbeat_period_ms
    old_hc = config.health_check_period_ms
    old_thresh = config.health_check_failure_threshold
    config.set("raylet_heartbeat_period_ms", 100)
    config.set("health_check_period_ms", 100)
    config.set("health_check_failure_threshold", 5)
    try:
        gcs = GcsServer()
        nm = NodeManager(
            gcs_address=gcs.address,
            session_dir=str(tmp_path / "session"),
            num_cpus=1, num_tpus=0, resources=None,
            object_store_memory=32 * 1024 * 1024,
            is_head=True, node_name="head")
        _wait_until(lambda: any(n.alive for n in gcs._nodes.values()),
                    msg="node registered")
        # Wedge: stop the heartbeat loop without closing the socket.
        nm._shutdown = True  # heartbeat/reap loops exit; conn stays open
        _wait_until(
            lambda: all(not n.alive for n in gcs._nodes.values()),
            timeout=30,
            msg="gcs declared the silent node dead")
    finally:
        config.set("raylet_heartbeat_period_ms", old_period)
        config.set("health_check_period_ms", old_hc)
        config.set("health_check_failure_threshold", old_thresh)
        try:
            nm._shutdown = False
            nm.shutdown()
        except Exception:
            pass
        gcs.close()
