"""Driver-side completion ingestion fast path (ISSUE 16 / SCALE_r10)
and the worker->driver shm completion segments (ISSUE 17): absorb
split off the lease conn thread, the shm completion ring, parallel
(work-stealing) wave collection, and same-node workers appending lease
completions straight into per-worker segments of the driver's ring.

The contract under test:

* with ``completion_absorb_enabled`` the lease conn thread only parks
  raw frames — a dedicated absorb executor unpickles and wakes waiters
  — and results are IDENTICAL to the classic inline-absorb wire
  (toggling the knob off restores the legacy ``lease_tasks_done``
  format byte-for-byte);
* NM-relayed completion-ring records land in the driver's inline cache
  and retire pending-return window entries; a full ring is a COUNTED
  no-op (the unconditional GCS relay still delivers), and the consumer
  catches up after the stall;
* records a dead NM left behind are plain shared memory: the driver
  finishes draining them — no stranded record, and redelivery is
  idempotent (no double-deliver);
* driver shutdown unlinks the ring file and its doorbell socket — no
  leaked mmap for the NM to produce into;
* a dying absorb stage surfaces as a typed ``CompletionAbsorbError``
  at ``get()``, never a silent hang;
* ``get()``/``wait()`` steal parked frames onto the caller thread when
  they would otherwise block, so a stalled absorb executor cannot
  stall collection.
"""

import glob
import os
import pickle
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import node_manager as nm_mod
from ray_tpu._private import worker as worker_mod
from ray_tpu.exceptions import CompletionAbsorbError


def _cluster(**system_config):
    return ray_tpu.init(num_cpus=2,
                        object_store_memory=128 * 1024 * 1024,
                        _system_config=system_config or None)


@pytest.fixture
def ray_cluster():
    ctx = _cluster()
    yield ctx
    ray_tpu.shutdown()


def _worker():
    return worker_mod.global_worker()


def _nm():
    return worker_mod._global_cluster.nm


def _wait_for(pred, timeout=15, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _record_blob(oid: bytes, value_blob: bytes) -> bytes:
    """A completion record exactly as worker_main pickles them into
    task_done_batch frames (the NM relays these blobs verbatim)."""
    return pickle.dumps({
        "task_id": b"\x01" * 24,
        "status": "ok",
        "objects": [(oid, len(value_blob))],
        "error": None,
        "node_id": "test-node",
        "inline": {oid: value_blob},
    }, protocol=5)


def _activate_ring(w):
    """Run one task (registration triggers off _note_pending_returns)
    and wait until the driver's consumer loop AND the NM's producer
    are both live."""

    @ray_tpu.remote
    def _poke():
        return 0

    assert ray_tpu.get(_poke.remote()) == 0
    _wait_for(lambda: w._comp_ring_state in (2, 3), msg="ring registration")
    assert w._comp_ring_state == 2, "ring registration failed"
    _wait_for(lambda: any(_nm()._completion_rings.values()),
              msg="NM producer registration")


# --------------------------------------------- stage 1: absorb split


def test_absorb_split_executes_identically():
    """Socket framing path (worker segments pinned off so completions
    arrive as lease_tasks_done_b frames): frames park in the ingest
    deque and a dedicated absorb thread (not the conn thread)
    unpickles them — and every result comes back exactly as the
    classic path would deliver it."""
    _cluster(worker_completion_ring_enabled=False)
    try:
        w = _worker()
        lm = w._lease_mgr
        assert lm is not None and lm._absorb_exec is not None

        @ray_tpu.remote
        def f(x):
            return x * 2

        assert ray_tpu.get([f.remote(i) for i in range(64)]) == [
            i * 2 for i in range(64)]
        # The executor actually ran (its worker thread only spawns on
        # the first submitted frame) and drained everything it parked.
        assert any(t.name.startswith("rtpu-completion-absorb")
                   for t in threading.enumerate())
        assert len(lm._ingest) == 0
    finally:
        ray_tpu.shutdown()


def test_absorb_disabled_classic_wire():
    """Knob off: no absorb executor exists, the ingest deque is never
    touched, and the worker ships the legacy lease_tasks_done dict —
    results still correct (off-path byte-identical behavior)."""
    _cluster(completion_absorb_enabled=False)
    try:
        w = _worker()
        lm = w._lease_mgr

        @ray_tpu.remote
        def f(x):
            return x * 3

        assert ray_tpu.get([f.remote(i) for i in range(64)]) == [
            i * 3 for i in range(64)]
        assert lm._absorb_exec is None
        assert len(lm._ingest) == 0
        assert not any(t.name.startswith("rtpu-completion-absorb")
                       for t in threading.enumerate())
    finally:
        ray_tpu.shutdown()


def test_absorb_failure_raises_typed_error(ray_cluster):
    """A frame the absorb stage cannot decode fails the lease's pending
    returns with CompletionAbsorbError — get() raises it promptly
    instead of hanging on a completion event nobody will ever set."""
    w = _worker()
    lm = w._lease_mgr

    @ray_tpu.remote
    def stall():
        time.sleep(60)

    ref = stall.remote()
    lm.flush_sends()
    _wait_for(lambda: len(lm._task_lease) >= 1, msg="lease in flight")
    lease = next(iter(lm._task_lease.values()))[0]
    # Drive the absorb path exactly as _drain_ingest would, with a
    # frame that cannot unpickle.
    lm._absorb_frame(lease, [b"\x80garbage-not-a-pickle"])
    with pytest.raises(CompletionAbsorbError):
        ray_tpu.get(ref, timeout=10)


# ------------------------------------------ stage 2: completion ring


def test_ring_records_absorb_into_inline_cache(ray_cluster):
    """An NM-relayed record lands its inline blob in the driver's
    process cache and retires the pending-returns window entry without
    any socket traffic."""
    w = _worker()
    _activate_ring(w)
    oid = os.urandom(28)
    w._pending_returns[oid] = None
    _nm()._relay_completion_rings([_record_blob(oid, b"payload-bytes")])
    _wait_for(lambda: oid in w._inline, msg="ring record absorbed")
    assert w._inline.get(oid) == b"payload-bytes"
    assert oid not in w._pending_returns


def test_ring_full_falls_back_counted(ray_cluster):
    """With the consumer stalled, a full ring makes append() refuse —
    the NM counts the drop (driver_completion_ring_full_total) and
    relies on the unconditional GCS relay; once the consumer resumes
    it drains the backlog and appends succeed again."""
    w = _worker()
    _activate_ring(w)
    nm = _nm()
    ent = next(ents[0] for ents in nm._completion_rings.values() if ents)
    producer = ent["producer"]

    w._comp_ring_pause = True   # consumer idles; head stops moving
    try:
        big = _record_blob(os.urandom(28), b"x" * 65536)
        for _ in range(4096):
            if not producer.append(big):
                break
        else:
            pytest.fail("ring never filled")

        counter = nm_mod._comp_ring_full_counter()
        before = sum(counter._values.values())
        nm._relay_completion_rings([_record_blob(os.urandom(28),
                                                 b"y" * 65536)])
        assert sum(counter._values.values()) > before
    finally:
        w._comp_ring_pause = False
    # Consumer catches up: the backlog drains and the ring takes
    # appends again.
    _wait_for(lambda: producer.append(_record_blob(os.urandom(28), b"z")),
              msg="ring drained after stall")


def test_nm_death_unconsumed_records_recovered(ray_cluster):
    """Records a dead NM left in the ring are plain shared memory: the
    driver finishes draining them (no stranded record) and redelivered
    blobs are idempotent (no double-deliver)."""
    w = _worker()
    _activate_ring(w)
    nm = _nm()
    ent = next(ents[0] for ents in nm._completion_rings.values() if ents)
    producer = ent["producer"]
    ring = w._comp_ring
    ring_path = ring.path

    oids = [os.urandom(28) for _ in range(3)]
    blobs = [_record_blob(o, b"val-%d" % i) for i, o in enumerate(oids)]

    w._comp_ring_pause = True
    try:
        for b in blobs:
            assert producer.append(b)
        # "NM dies": the producer goes away mid-ring. close() flags the
        # ring closed and rings the bell but NEVER unlinks — the
        # unconsumed records stay valid shm for the driver to finish.
        producer.close()
        with nm._lock:
            for ents in nm._completion_rings.values():
                ents[:] = [e for e in ents if e is not ent]
    finally:
        w._comp_ring_pause = False

    for i, o in enumerate(oids):
        _wait_for(lambda o=o: o in w._inline, msg="post-death drain")
        assert w._inline.get(o) == b"val-%d" % i
    # Redelivery (the GCS copy arriving later, or a replayed frame) is
    # a no-op, not a double-deliver.
    for b in blobs:
        w._absorb_completion_record(b)
    for i, o in enumerate(oids):
        assert w._inline.get(o) == b"val-%d" % i
    # Producer closed + drained => the consumer loop exits and unlinks.
    _wait_for(lambda: not os.path.exists(ring_path),
              msg="ring unlink after producer close")


def test_driver_shutdown_unlinks_ring_files():
    """Driver shutdown must unlink both the ring file and the doorbell
    socket — a leaked mmap would have the NM producing into a file no
    one will ever drain."""
    _cluster()
    try:
        w = _worker()
        _activate_ring(w)
        path = w._comp_ring.path
        assert os.path.exists(path)
    finally:
        ray_tpu.shutdown()
    deadline = time.time() + 5
    while time.time() < deadline and os.path.exists(path):
        time.sleep(0.05)
    assert not os.path.exists(path), "ring file leaked"
    assert not os.path.exists(path + ".bell"), "doorbell socket leaked"


def test_ring_disabled_never_registers():
    """Knob off: the driver never creates a ring file and the NM never
    gains a producer — the socket/GCS path carries everything."""
    _cluster(completion_ring_enabled=False)
    try:
        w = _worker()

        @ray_tpu.remote
        def f():
            return 7

        assert ray_tpu.get(f.remote()) == 7
        time.sleep(0.2)
        assert w._comp_ring_state == 0
        assert w._comp_ring is None
        assert not any(_nm()._completion_rings.values())
    finally:
        ray_tpu.shutdown()


# --------------------------------- stage 3: parallel wave collection


def test_get_and_wait_steal_parked_frames():
    """With the absorb executor wedged (frames park but nothing drains
    them), a caller blocking on a lease completion steals the parked
    frame onto its OWN thread: get() returns the value and wait()
    reports readiness without the GCS round trip — neither idles on an
    event only the dead executor would have set. (Worker segments
    pinned off: the stall under test is the SOCKET frame path.)"""
    _cluster(worker_completion_ring_enabled=False)
    try:
        w = _worker()
        lm = w._lease_mgr
        real_submit = lm._absorb_submit
        lm._absorb_submit = lambda: None   # frames park; nothing drains
        try:

            @ray_tpu.remote
            def f(x):
                return x + 100

            ref = f.remote(7)
            lm.flush_sends()
            _wait_for(lambda: len(lm._ingest) > 0, msg="parked frame")
            assert ray_tpu.get(ref, timeout=15) == 107
            assert len(lm._ingest) == 0  # the caller thread absorbed it

            ref2 = f.remote(8)
            lm.flush_sends()
            _wait_for(lambda: len(lm._ingest) > 0,
                      msg="second parked frame")
            ready, rest = ray_tpu.wait([ref2], num_returns=1, timeout=15)
            assert ready == [ref2] and not rest
            assert ray_tpu.get(ref2, timeout=15) == 108
        finally:
            lm._absorb_submit = real_submit
    finally:
        ray_tpu.shutdown()


def test_steal_disabled_gate():
    """completion_steal_enabled=False: steal_absorb() is a hard no-op
    and blocking collection leans on the absorb executor alone."""
    _cluster(completion_steal_enabled=False)
    try:
        w = _worker()
        lm = w._lease_mgr
        assert lm._steal is False
        assert lm.steal_absorb() is False

        @ray_tpu.remote
        def f(x):
            return x - 1

        assert ray_tpu.get([f.remote(i) for i in range(16)]) == [
            i - 1 for i in range(16)]
    finally:
        ray_tpu.shutdown()


# -------------------- stage 4: worker->driver segment transport


def _activate_segment(w):
    """Run lease traffic until the driver's ring is live AND at least
    one same-node worker has attached its completion segment (the
    advertise -> create -> map -> ack handshake is async with respect
    to task completion, so poke until it lands)."""

    @ray_tpu.remote
    def _poke(x):
        return x

    assert ray_tpu.get(_poke.remote(1)) == 1
    _wait_for(lambda: w._comp_ring_state in (2, 3), msg="ring registration")
    assert w._comp_ring_state == 2, "ring registration failed"

    def seg_live():
        ray_tpu.get([_poke.remote(i) for i in range(4)])
        return bool(w._comp_segments)

    _wait_for(seg_live, timeout=20, msg="worker segment attach")


def test_worker_segment_roundtrip(ray_cluster):
    """Default knobs: same-node leased workers attach per-worker
    segments under the driver's ring path and sustained lease traffic
    flows through them with correct results; the segments drain to
    empty when the wave completes."""
    w = _worker()
    _activate_segment(w)
    ring_path = w._comp_ring.path
    assert all(p.startswith(ring_path + ".w") for p in w._comp_segments)

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get([f.remote(i) for i in range(300)]) == [
        i + 1 for i in range(300)]
    # Wave done => every record was absorbed and committed.
    _wait_for(lambda: all(not e["seg"].pending()
                          for e in w._comp_segments.values()),
              msg="segments drained")


def test_worker_sigkill_midstream_no_loss_no_leak(ray_cluster):
    """SIGKILL every leased worker mid-wave: records the workers
    published before dying drain from their segments (tail publishes
    after payload, so a torn append is invisible — never a corrupt
    record), the unfinished remainder re-runs via the scheduled
    fallback, and NO segment file outlives its worker (driver
    force-unlink + NM registry backstop)."""
    w = _worker()
    _activate_segment(w)
    ring_path = w._comp_ring.path
    nm = _nm()

    @ray_tpu.remote
    def f(x):
        return x * 7

    refs = [f.remote(i) for i in range(80)]
    with nm._lock:
        pids = [h.proc.pid for h in nm._workers.values()]
    assert pids
    for pid in pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    # At-least-once: every result arrives (segment drain for completed
    # records, scheduled re-run for the rest) and none is corrupt.
    assert ray_tpu.get(refs, timeout=90) == [i * 7 for i in range(80)]
    # The dead workers' segment files are gone (replacement workers may
    # have attached fresh ones; those are live, not leaks).
    _wait_for(lambda: set(glob.glob(ring_path + ".w*")) <=
              set(w._comp_segments),
              msg="dead-worker segment cleanup")


def test_worker_segment_full_falls_back():
    """A tiny segment + a stalled consumer: the worker fills the
    segment, overflow records fall back to the socket
    (lease_tasks_done_b), and when the consumer resumes the backlogged
    ring records are redelivery-idempotent against the socket copies —
    every result correct exactly once."""
    _cluster(worker_completion_ring_bytes=4096)
    try:
        w = _worker()
        _activate_segment(w)

        @ray_tpu.remote
        def f(x):
            # ~1 KiB inlined record: THREE completions fill the 4 KiB
            # segment regardless of pipeline depth, so the stall test
            # never depends on how many tasks are in flight at once.
            return (x, b"v" * 1024)

        w._comp_ring_pause = True   # head stops: segment backlog grows
        try:
            refs = [f.remote(i) for i in range(150)]
            # The segment actually filled (fallback engaged): with the
            # consumer paused, published bytes approach the 4 KiB cap.
            _wait_for(lambda: any(
                e["seg"].backlog_bytes() > 2048
                for e in w._comp_segments.values()),
                msg="segment backlog under stall")
        finally:
            w._comp_ring_pause = False
        assert ray_tpu.get(refs, timeout=60) == [
            (i, b"v" * 1024) for i in range(150)]
    finally:
        ray_tpu.shutdown()


def test_driver_shutdown_unlinks_segments():
    """Driver shutdown with live worker producers: the consumer loop
    force-unlinks every mapped segment and glob-sweeps the ring's
    namespace — no comring_* file (main ring, bell, or segment)
    survives the driver."""
    _cluster()
    try:
        w = _worker()
        _activate_segment(w)
        ring_path = w._comp_ring.path
        seg_paths = list(w._comp_segments)
        assert seg_paths
    finally:
        ray_tpu.shutdown()
    deadline = time.time() + 5
    leftovers = lambda: ([p for p in seg_paths + [ring_path,
                                                  ring_path + ".bell"]
                          if os.path.exists(p)]
                         + glob.glob(ring_path + ".w*"))
    while time.time() < deadline and leftovers():
        time.sleep(0.05)
    assert not leftovers(), f"leaked shm files: {leftovers()}"


def test_worker_ring_disabled_socket_only():
    """worker_completion_ring_enabled=False: no segment ever attaches
    (the driver never advertises) while the NM-relay main ring keeps
    working — the socket carries every lease completion, results
    identical."""
    _cluster(worker_completion_ring_enabled=False)
    try:
        w = _worker()

        @ray_tpu.remote
        def f(x):
            return x - 5

        assert ray_tpu.get([f.remote(i) for i in range(64)]) == [
            i - 5 for i in range(64)]
        time.sleep(0.3)
        assert not w._comp_segments
        assert not w._worker_ring_enabled
    finally:
        ray_tpu.shutdown()


def test_worker_ring_without_main_ring():
    """completion_ring_enabled=False with the worker knob on: there is
    no driver ring for segments to attach next to, so the whole shm
    family stays off and the socket path carries everything — knob
    drift across the pair is safe in both directions."""
    _cluster(completion_ring_enabled=False)
    try:
        w = _worker()

        @ray_tpu.remote
        def f(x):
            return x * 11

        assert ray_tpu.get([f.remote(i) for i in range(64)]) == [
            i * 11 for i in range(64)]
        time.sleep(0.3)
        assert w._comp_ring is None
        assert not w._comp_segments
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------------------
# Doorbell coalescing (ISSUE 18): append_batch publishes a whole flush
# batch with ONE tail store and AT MOST ONE bell write, vs one bell per
# record on the per-append path while the consumer is parked.


def _raw_ring(tmp_path, capacity=1 << 16):
    from ray_tpu._private import completion_ring as cr

    path = str(tmp_path / "ring")
    cons = cr.RingConsumer(path, capacity=capacity)
    prod = cr.RingProducer(path)
    prod.connect_bell()
    return cons, prod


def _count_bells(prod):
    bells = {"n": 0}
    orig = prod._ring_bell

    def counting():
        bells["n"] += 1
        orig()

    prod._ring_bell = counting
    return bells


def test_batch_flush_rings_at_most_one_bell(tmp_path):
    """64 records through append_batch while the consumer is parked:
    exactly ONE bell write for the whole flush, every record published
    and drainable — versus the per-append path, which (shallow backlog)
    rings once per record."""
    cons, prod = _raw_ring(tmp_path)
    try:
        cons.set_parked(True)
        bells = _count_bells(prod)
        blobs = [b"r%03d" % i for i in range(64)]
        assert prod.append_batch(blobs) == 64
        assert bells["n"] == 1
        got, new_head = cons.drain(max_records=128)
        assert got == blobs
        cons.commit(new_head)
        # The one datagram actually landed on the consumer's bell
        # socket — the wakeup was sent, not just counted.
        cons._bell.settimeout(1.0)
        assert cons._bell.recv(64) == b"!"

        # Contrast: the same 64 records via per-record append ring 64
        # bells (backlog stays shallow, so no rate limit applies).
        bells["n"] = 0
        for b in blobs:
            assert prod.append(b)
        assert bells["n"] == 64
        got, new_head = cons.drain(max_records=128)
        assert got == blobs
        cons.commit(new_head)
    finally:
        prod.close()
        cons.close()


def test_batch_flush_unparked_consumer_no_bell(tmp_path):
    """An actively-draining (unparked) consumer costs a batch append
    zero bell writes — pure memcpy plus one tail publish."""
    cons, prod = _raw_ring(tmp_path)
    try:
        bells = _count_bells(prod)
        assert prod.append_batch([b"a", b"b", b"c"]) == 3
        assert bells["n"] == 0
        got, new_head = cons.drain()
        assert got == [b"a", b"b", b"c"]
        cons.commit(new_head)
    finally:
        prod.close()
        cons.close()


def test_batch_flush_no_lost_wakeup(tmp_path):
    """A consumer genuinely parked in park_wait() is woken by the one
    coalesced bell and drains the whole batch — coalescing must never
    strand records behind a missing wakeup."""
    cons, prod = _raw_ring(tmp_path)
    drained: list = []
    woke = threading.Event()

    def consumer_loop():
        while not cons.stopped:
            got, new_head = cons.drain()
            if got:
                drained.extend(got)
                cons.commit(new_head)
                woke.set()
                return
            cons.park_wait()

    t = threading.Thread(target=consumer_loop, daemon=True)
    try:
        t.start()
        # Wait until the consumer is actually parked (flag visible)
        # before appending, so the bell is load-bearing for the wakeup.
        deadline = time.time() + 5
        while not cons._get(32) and time.time() < deadline:
            time.sleep(0.001)
        bells = _count_bells(prod)
        blobs = [b"wake%02d" % i for i in range(16)]
        assert prod.append_batch(blobs) == 16
        assert bells["n"] <= 1
        assert woke.wait(timeout=5), "parked consumer never woke"
        assert drained == blobs
    finally:
        cons.stopped = True
        t.join(timeout=5)
        prod.close()
        cons.close()


def test_batch_flush_partial_on_full_ring(tmp_path):
    """A batch that overflows the ring publishes its leading records
    (short count back to the caller for socket fallback) and still
    rings at most one bell; records never tear."""
    cons, prod = _raw_ring(tmp_path, capacity=256)
    try:
        cons.set_parked(True)
        bells = _count_bells(prod)
        blobs = [b"x" * 60 for _ in range(8)]   # 64 B/record: 4 fit
        appended = prod.append_batch(blobs)
        assert 0 < appended < 8
        assert bells["n"] == 1
        got, new_head = cons.drain()
        assert got == blobs[:appended]
        cons.commit(new_head)
        # Drained ring takes the remainder; a fresh batch on an empty
        # ring appends fully.
        assert prod.append_batch(blobs[appended:]) == 8 - appended
    finally:
        prod.close()
        cons.close()
