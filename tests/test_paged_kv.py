"""Paged (block-granular) KV cache tests: block-pool accounting,
chunked-prefill equivalence vs single-shot prefill, paged decode parity
with generate(), preemption-by-recompute continuity, long-context
admission failing cleanly on pool exhaustion, and the KV byte budget
that the reserved layout trips but the paged pool fits."""

import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.exceptions import EngineFailedError, KVCacheExhaustedError
from ray_tpu.models import GPTConfig, init_params
from ray_tpu.models.generate import (
    decode_step_paged, generate, init_paged_pool, prefill_chunk_paged,
    prefill_slot, prefill_slots,
)
from ray_tpu.serve.llm.engine import EngineConfig, InflightBatchEngine
from ray_tpu.serve.llm.paged import BlockPool
from ray_tpu.serve.llm.replicas import _build_model

BASE = dict(preset="tiny", model_overrides={"dtype": "float32"},
            max_slots=4, max_len=64, prompt_buckets=(16,),
            max_new_tokens=16)
PROMPT = [5, 9, 2, 11, 3]
N = 8


@pytest.fixture(scope="module")
def model():
    cfg, params = _build_model(EngineConfig.from_dict(BASE))
    return cfg, params


def _ref(cfg, params, prompt, n, seed=0, **kw):
    return [int(x) for x in generate(
        params, jnp.asarray([prompt], jnp.int32), jax.random.key(0),
        cfg=cfg, max_new_tokens=n, temperature=kw.get("temperature", 0.0),
        top_k=kw.get("top_k", 0))[0]]


# ------------------------------------------------------------ block pool


def test_block_pool_accounting():
    pool = BlockPool(9, 4)          # 8 usable blocks (block 0 scratch)
    assert pool.capacity == 8 and pool.available() == 8
    assert pool.blocks_for(1) == 1 and pool.blocks_for(4) == 1
    assert pool.blocks_for(5) == 2 and pool.blocks_for(0) == 0
    assert pool.can_fit(32) and not pool.can_fit(33)

    a = pool.alloc(3)
    b = pool.alloc(5)
    assert len(a) == 3 and len(b) == 5 and pool.available() == 0
    assert 0 not in a + b            # scratch never handed out
    assert pool.alloc(1) is None     # exhausted: all-or-nothing None
    assert pool.available() == 0     # failed alloc took nothing
    pool.free(a)
    assert pool.available() == 3 and pool.used() == 5
    with pytest.raises(ValueError, match="double free"):
        pool.free([a[0]])
    with pytest.raises(ValueError, match="invalid"):
        pool.free([0])
    pool.free(b)
    assert pool.used() == 0
    s = pool.stats()
    assert s["kv_blocks_alloc_total"] == 8
    assert s["kv_blocks_freed_total"] == 8


# ------------------------------------------------- program-level parity


def test_chunked_prefill_equivalence_vs_single_shot(model):
    """Chunked prefill writes the SAME KV rows and samples the same
    first token as single-shot prefill_slot (greedy), for chunk sizes
    that do and do not divide the prompt length."""
    cfg, params = model
    prompt = [int(t) for t in jax.random.randint(
        jax.random.key(3), (11,), 0, cfg.vocab_size)]
    one = np.zeros((1, 16), np.int32)
    one[0, :len(prompt)] = prompt
    ref_first, ref_kv = prefill_slot(
        params, jnp.asarray(one), jnp.int32(len(prompt)), jnp.int32(0),
        cfg=cfg)

    for C in (3, 4, 16):
        bs, M, S, NB = 4, 8, 2, 20
        pool = init_paged_pool(cfg, NB, bs, S, M)
        bt = np.zeros((S, M), np.int32)
        bt[0, :3] = [5, 9, 2]        # ceil(11/4) = 3 blocks, any order
        kvp = {"k": pool["k"], "v": pool["v"]}
        start, first = 0, None
        while start < len(prompt):
            chunk = prompt[start:start + C]
            padded = np.zeros((1, C), np.int32)
            padded[0, :len(chunk)] = chunk
            first, kvp = prefill_chunk_paged(
                params, kvp, jnp.asarray(bt[0]), jnp.asarray(padded),
                jnp.int32(start), jnp.int32(len(chunk)), jnp.int32(0),
                cfg=cfg, block_size=bs)
            start += len(chunk)
        assert int(first[0]) == int(ref_first[0]), C
        # The pages hold the same K rows the contiguous prefill built
        # (gather them back in logical order over the real positions).
        flat = []
        for p in range(len(prompt)):
            flat.append(int(bt[0][p // bs]) * bs + p % bs)
        got_k = np.asarray(kvp["k"])[:, flat]
        np.testing.assert_allclose(
            got_k, np.asarray(ref_kv["k"])[:, 0, :len(prompt)],
            atol=1e-5)


def test_paged_decode_parity_and_pool_state(model):
    """Full paged path (chunked prefill + decode_step_paged) reproduces
    generate() greedy, with the sequence in non-contiguous pages."""
    cfg, params = model
    prompt = [7, 3, 1, 12, 9, 4, 2]
    ref = _ref(cfg, params, prompt, N)
    bs, M, S, NB = 4, 8, 3, 16
    pool = init_paged_pool(cfg, NB, bs, S, M)
    bt = np.zeros((S, M), np.int32)
    bt[2, :4] = [11, 3, 7, 1]        # deliberately scrambled pages
    pool["block_tables"] = jnp.asarray(bt)
    kvp = {"k": pool["k"], "v": pool["v"]}
    first, kvp = prefill_chunk_paged(
        params, kvp, jnp.asarray(bt[2]),
        jnp.asarray(np.asarray([prompt], np.int32)), jnp.int32(0),
        jnp.int32(len(prompt)), jnp.int32(0), cfg=cfg,
        block_size=bs)
    pool["k"], pool["v"] = kvp["k"], kvp["v"]
    lengths = np.zeros((S,), np.int32)
    lengths[2] = len(prompt)
    pool["lengths"] = jnp.asarray(lengths)
    toks = [int(first[0])]
    last = np.zeros((S,), np.int32)
    last[2] = toks[0]
    active = np.zeros((S,), bool)
    active[2] = True
    for _ in range(N - 1):
        nxt, pool = decode_step_paged(
            params, pool, jnp.asarray(last), jnp.asarray(active),
            jnp.zeros((S,), jnp.int32), cfg=cfg, block_size=bs)
        toks.append(int(nxt[2]))
        last[2] = int(nxt[2])
    assert toks == ref


def test_prefill_slots_batch_matches_single(model):
    """Batched prefill (the prefill pool's micro-batcher program) is
    row-for-row identical to per-prompt prefill_slot, sampled mode."""
    cfg, params = model
    prompts = [[5, 9, 2], [7, 7, 7, 7, 1, 3], [3, 1, 4, 1, 5]]
    padded = np.zeros((4, 16), np.int32)   # one dummy pad row
    lens = np.ones((4,), np.int32)
    seeds = np.zeros((4,), np.int32)
    for i, p in enumerate(prompts):
        padded[i, :len(p)] = p
        lens[i] = len(p)
        seeds[i] = 10 + i
    firsts, kv = prefill_slots(
        params, jnp.asarray(padded), jnp.asarray(lens),
        jnp.asarray(seeds), cfg=cfg, temperature=0.9, top_k=8)
    for i, p in enumerate(prompts):
        one = np.zeros((1, 16), np.int32)
        one[0, :len(p)] = p
        f1, kv1 = prefill_slot(
            params, jnp.asarray(one), jnp.int32(len(p)),
            jnp.int32(10 + i), cfg=cfg, temperature=0.9, top_k=8)
        assert int(f1[0]) == int(firsts[i]), i
        np.testing.assert_allclose(np.asarray(kv["k"][:, i]),
                                   np.asarray(kv1["k"][:, 0]), atol=1e-5)


# ------------------------------------------------------- engine behavior


def test_paged_engine_parity_and_no_block_leak(model):
    cfg, params = model
    ec = EngineConfig.from_dict(dict(BASE, paged_kv=True,
                                     kv_block_size=4, prefill_chunk=4))
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        ref = _ref(cfg, params, PROMPT, N)
        assert eng.generate(PROMPT, N) == ref
        # Long prompt (beyond every bucket): chunked prefill covers it.
        long_prompt = [1 + (i % 40) for i in range(37)]
        assert eng.generate(long_prompt, 6) == _ref(cfg, params,
                                                    long_prompt, 6)
        s = eng.stats()
        assert s["paged_kv"] is True
        assert s["kv_blocks_used"] == 0, s   # every block returned
        assert s["kv_blocks_alloc_total"] == s["kv_blocks_freed_total"]
    finally:
        eng.stop()


def test_paged_engine_contention_preempts_and_resumes_exactly(model):
    """A pool too small for all sequences at once: the engine preempts
    by recompute (free blocks -> requeue -> re-prefill prompt+generated)
    and every request still gets EXACTLY its solo-run tokens."""
    cfg, params = model
    ec = EngineConfig.from_dict(dict(
        BASE, paged_kv=True, kv_block_size=4, prefill_chunk=4,
        kv_num_blocks=7))   # 6 usable blocks = 24 tokens of KV
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        prompts = [PROMPT, [7, 7, 3], [2, 4, 6, 8]]
        # Each sequence needs ceil((len+8)/4) ~ 4 blocks; three do not
        # fit 6 blocks -> guaranteed contention.
        rids = [eng.submit(p, N, seed=0) for p in prompts]
        outs = [list(itertools.chain.from_iterable(
            eng.stream(r, max_wait_s=5))) for r in rids]
        for p, out in zip(prompts, outs):
            assert out == _ref(cfg, params, p, N), p
        s = eng.stats()
        assert s["kv_blocks_used"] == 0
    finally:
        eng.stop()


def test_paged_engine_sampled_resume_continuity(model):
    """Preemption continuity holds under SAMPLING too: the per-request
    (seed, position) keys make recompute-resume reproduce the same
    continuation the uninterrupted run produces."""
    cfg, params = model
    tight = EngineConfig.from_dict(dict(
        BASE, paged_kv=True, kv_block_size=4, prefill_chunk=4,
        kv_num_blocks=7, temperature=0.9, top_k=16))
    solo = EngineConfig.from_dict(dict(
        BASE, paged_kv=True, kv_block_size=4, prefill_chunk=4,
        temperature=0.9, top_k=16))
    eng_solo = InflightBatchEngine(params, cfg, solo)
    eng_tight = InflightBatchEngine(params, cfg, tight)
    try:
        jobs = ((3, PROMPT), (4, [9, 9, 1, 2]), (5, [6, 2]))
        expect = {}
        for seed, p in jobs:
            rid = eng_solo.submit(p, N, seed=seed)
            expect[seed] = list(itertools.chain.from_iterable(
                eng_solo.stream(rid, max_wait_s=5)))
        rids = {seed: eng_tight.submit(p, N, seed=seed)
                for seed, p in jobs}
        for seed, _ in jobs:
            got = list(itertools.chain.from_iterable(
                eng_tight.stream(rids[seed], max_wait_s=5)))
            assert got == expect[seed], seed
    finally:
        eng_solo.stop()
        eng_tight.stop()


def test_long_context_admission_fails_cleanly_when_pool_exhausted(model):
    """A sequence that can NEVER fit the pool raises typed at submit —
    not a parked request, not an engine wedge — and the engine keeps
    serving others afterwards."""
    cfg, params = model
    ec = EngineConfig.from_dict(dict(
        BASE, paged_kv=True, kv_block_size=4, kv_num_blocks=5,
        prefill_chunk=4))   # 4 usable blocks = 16 tokens
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        with pytest.raises(KVCacheExhaustedError, match="KV blocks"):
            eng.submit([1] * 12, 8)           # 20 tokens > 16
        # Still serving sequences that fit.
        assert eng.generate([4, 2], 4) == _ref(cfg, params, [4, 2], 4)
    finally:
        eng.stop()


def test_kv_byte_budget_reserved_ooms_paged_serves(model):
    """The memory-side unlock, engine-level: under one KV byte budget
    the reserved layout (slots x max_len up front) refuses to
    construct, while a paged pool admits and serves a long context."""
    cfg, params = model
    long_cfg = dict(BASE, max_len=48, max_slots=4)
    per_tok = EngineConfig.from_dict(long_cfg).kv_bytes_per_token(cfg)
    budget = per_tok * 100               # < 4 slots x 48 tokens = 192
    with pytest.raises(KVCacheExhaustedError, match="max_kv_bytes"):
        InflightBatchEngine(params, cfg, EngineConfig.from_dict(
            dict(long_cfg, max_kv_bytes=budget)))
    eng = InflightBatchEngine(params, cfg, EngineConfig.from_dict(
        dict(long_cfg, paged_kv=True, kv_block_size=4,
             kv_num_blocks=25, max_kv_bytes=budget,   # 100 tokens
             prefill_chunk=8)))
    try:
        long_prompt = [1 + (i % 30) for i in range(40)]   # > max bucket
        out = eng.generate(long_prompt, 6)
        assert out == _ref(cfg, params, long_prompt, 6)
    finally:
        eng.stop()


def test_cancel_frees_slot_and_blocks(model):
    cfg, params = model
    ec = EngineConfig.from_dict(dict(BASE, paged_kv=True,
                                     kv_block_size=4, prefill_chunk=4,
                                     max_new_tokens=64, max_len=64))
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        rid = eng.submit([1, 2, 3], 50)
        deadline = time.time() + 10
        while time.time() < deadline and \
                eng.stats()["busy_slots"] == 0:
            time.sleep(0.02)
        assert eng.stats()["busy_slots"] >= 1
        eng.cancel(rid)
        deadline = time.time() + 10
        while time.time() < deadline and (
                eng.stats()["kv_blocks_used"] or
                eng.stats()["busy_slots"]):
            time.sleep(0.02)
        s = eng.stats()
        assert s["kv_blocks_used"] == 0 and s["busy_slots"] == 0, s
        with pytest.raises(KeyError):
            eng.drain(rid, max_wait_s=0.1)
    finally:
        eng.stop()


def test_poison_frees_all_blocks(model):
    """A scheduler-side failure fails every request AND returns every
    block to the pool — no leak across the poison path.

    Deterministic via fault injection: the 2nd decode step with live
    work raises inside the scheduler loop, so the poison lands while
    both requests hold blocks BY CONSTRUCTION. (Polling kv_blocks_used
    from outside races a warm-cache engine that can run the whole
    workload between two polls.)"""
    cfg, params = model
    ec = EngineConfig.from_dict(dict(BASE, paged_kv=True,
                                     kv_block_size=4, prefill_chunk=4,
                                     fault_inject="step_error:after=2"))
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        rids = [eng.submit(PROMPT, 32), eng.submit([4, 4], 32)]
        for rid in rids:
            # In-flight requests surface the poison as EngineFailedError
            # (carrying a resume descriptor); a fully-drained rid raises
            # KeyError on the next pull.
            with pytest.raises((EngineFailedError, KeyError)):
                while True:
                    eng.drain(rid, max_wait_s=0.2)
        s = eng.stats()
        assert s["kv_blocks_alloc_total"] > 0   # blocks WERE in play
        assert s["kv_blocks_used"] == 0, s      # ...and every one returned
        # The engine recovers: the injected fault fires once, new work
        # still runs.
        assert eng.generate([3, 1], 4) == _ref(cfg, params, [3, 1], 4)
    finally:
        eng.stop()


def test_prefill_micro_batcher_concurrent_parity_and_rotation(model):
    """Concurrent prefill calls batched into one program run return
    row-for-row what per-prompt prefill_slot returns, and under
    SUSTAINED arrivals leadership rotates — no caller is stuck serving
    other people's batches until a momentary drain (every call returns
    well inside the follow timeout)."""
    import threading

    from ray_tpu.serve.llm.replicas import _PrefillBatcher

    cfg, params = model
    ec = EngineConfig.from_dict(dict(BASE, prefill_batch_size=4,
                                     prefill_batch_window_ms=5.0))
    batcher = _PrefillBatcher(params, cfg, ec)
    prompts = [[1 + i, 5, 9, 2][:2 + i % 3] for i in range(24)]
    results = [None] * len(prompts)
    errors = []

    def one(i):
        try:
            results[i] = batcher.run(prompts[i], 16, seed=i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    # Three staggered waves -> the queue never fully drains between
    # waves, the regime where a drain-gated leader would be stuck.
    threads = []
    for wave in range(3):
        ws = [threading.Thread(target=one, args=(wave * 8 + j,))
              for j in range(8)]
        for t in ws:
            t.start()
        threads += ws
        time.sleep(0.03)
    deadline = time.time() + 60
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.time()))
    assert not errors, errors
    assert all(r is not None for r in results), "a caller never returned"

    for i, p in enumerate(prompts):
        one_p = np.zeros((1, 16), np.int32)
        one_p[0, :len(p)] = p
        f1, kv1 = prefill_slot(params, jnp.asarray(one_p),
                               jnp.int32(len(p)), jnp.int32(i), cfg=cfg)
        first, kv = results[i]
        assert first == int(f1[0]), i
        np.testing.assert_allclose(np.asarray(kv["k"])[:, 0],
                                   np.asarray(kv1["k"])[:, 0], atol=1e-5)


def test_sequence_filling_max_len_exactly_frees_blocks(model):
    """A request generating right up to the cache boundary (prompt +
    budget == max_len) completes and returns every block — the
    off-by-one-prone edge of the growth path (the last token's KV row
    lands in the last allocated page)."""
    cfg, params = model
    ec = EngineConfig.from_dict(dict(
        BASE, max_len=16, max_new_tokens=16, paged_kv=True,
        kv_block_size=4, prefill_chunk=4))
    eng = InflightBatchEngine(params, cfg, ec)
    try:
        budget = 16 - len(PROMPT)
        toks = list(itertools.chain.from_iterable(
            eng.stream(eng.submit(PROMPT, budget), max_wait_s=5)))
        assert toks == _ref(cfg, params, PROMPT, budget)
        assert eng.stats()["kv_blocks_used"] == 0
    finally:
        eng.stop()
