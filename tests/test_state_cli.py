"""State API and CLI tests."""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu.experimental import state


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_state_api(ray_cluster):
    @ray_tpu.remote
    def work(x):
        return x + 1

    @ray_tpu.remote
    class Holder:
        def get(self):
            return 1

    refs = [work.remote(i) for i in range(3)]
    h = Holder.remote()
    assert ray_tpu.get(h.get.remote()) == 1
    ray_tpu.get(refs)

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["Alive"]

    actors = state.list_actors()
    assert any(a["class_name"] == "Holder" for a in actors)
    aid = next(a["actor_id"] for a in actors
               if a["class_name"] == "Holder")
    assert state.get_actor(aid)["class_name"] == "Holder"

    deadline = time.time() + 10
    while time.time() < deadline:
        tasks = state.list_tasks()
        done = [t for t in tasks if t["name"] == "work"
                and t["state"] == "FINISHED"]
        if len(done) == 3:
            break
        time.sleep(0.2)
    assert len(done) == 3

    summary = state.summarize_tasks()
    assert summary.get("work", {}).get("FINISHED", 0) >= 3

    objs = state.list_objects()
    assert isinstance(objs, list)

    jobs = state.list_jobs()
    assert len(jobs) >= 1 and jobs[0]["state"] == "RUNNING"


def test_cli_head_lifecycle(tmp_path):
    """ray_tpu start --head / status / list nodes / stop, via real
    subprocesses (reference: ray start smoke tests)."""
    env = {"PATH": "/usr/bin:/bin:/usr/local/bin",
           "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
           "PYTHONPATH": "/root/repo", "HOME": "/root"}

    def run(*args, timeout=60):
        return subprocess.run(
            [sys.executable, "-m", "ray_tpu", *args],
            capture_output=True, text=True, timeout=timeout, env=env)

    # ensure no stale head
    run("stop")
    out = run("start", "--head", "--num-cpus", "2")
    assert out.returncode == 0, out.stderr
    assert "started at" in out.stdout
    try:
        st = run("status")
        assert st.returncode == 0, st.stderr
        assert "1 alive" in st.stdout

        ls = run("list", "nodes")
        rows = json.loads(ls.stdout)
        assert len(rows) == 1
    finally:
        out = run("stop")
        assert out.returncode == 0


def test_dump_stacks_reaches_worker_logs(capfd):
    """`ray_tpu stack` plumbing: dump_stacks fans SIGUSR2 to workers and
    the faulthandler tracebacks stream back through worker logs."""
    import time as _time

    import ray_tpu
    from ray_tpu._private import worker as worker_mod

    ray_tpu.init(num_cpus=2, log_to_driver=True)
    try:
        @ray_tpu.remote
        class Sleeper:
            def nap(self, s):
                _time.sleep(s)
                return True

        s = Sleeper.remote()
        ref = s.nap.remote(5)
        _time.sleep(0.5)  # actor mid-nap
        n = worker_mod.require_worker().gcs.request("dump_stacks", {})
        assert n >= 1
        deadline = _time.time() + 15
        buf = ""
        while _time.time() < deadline:
            out, err = capfd.readouterr()
            buf += out + err
            if "Current thread" in buf or "Thread 0x" in buf:
                break
            _time.sleep(0.3)
        assert "Thread" in buf, buf[-400:]
        assert ray_tpu.get(ref, timeout=30)
    finally:
        ray_tpu.shutdown()
