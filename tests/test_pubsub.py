"""GCS pub/sub channels (reference: src/ray/pubsub/publisher.h +
ray._private.gcs_pubsub)."""

import queue
import time

import pytest

import ray_tpu
from ray_tpu.experimental import pubsub


@pytest.fixture
def ray_2cpu():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_publish_subscribe_roundtrip(ray_2cpu):
    sub = pubsub.subscribe("alerts")
    pubsub.publish("alerts", {"sev": 1, "msg": "hi"})
    assert sub.get(timeout=10) == {"sev": 1, "msg": "hi"}
    sub.unsubscribe()


def test_publish_from_worker_reaches_driver(ray_2cpu):
    sub = pubsub.subscribe("events")

    @ray_tpu.remote
    def announce(i):
        from ray_tpu.experimental import pubsub as ps

        ps.publish("events", {"i": i})
        return i

    assert ray_tpu.get(announce.remote(7), timeout=60) == 7
    assert sub.get(timeout=10) == {"i": 7}


def test_actor_state_channel(ray_2cpu):
    """The GCS publishes actor lifecycle transitions on actor_state."""
    sub = pubsub.subscribe("actor_state")

    @ray_tpu.remote
    class Blip:
        def ping(self):
            return True

    b = Blip.remote()
    assert ray_tpu.get(b.ping.remote(), timeout=60)
    msg = sub.get(timeout=15)
    assert msg["state"] == "ALIVE"
    assert msg["class_name"] == "Blip"
    ray_tpu.kill(b)
    deadline = time.time() + 15
    while time.time() < deadline:
        try:
            msg = sub.get(timeout=5)
        except queue.Empty:
            continue
        if msg["state"] == "DEAD":
            return
    raise AssertionError("never saw the DEAD transition")


def test_unsubscribed_channel_silent(ray_2cpu):
    sub = pubsub.subscribe("chan_a")
    pubsub.publish("chan_b", "nope")
    pubsub.publish("chan_a", "yes")
    assert sub.get(timeout=10) == "yes"
    with pytest.raises(queue.Empty):
        sub.get_nowait()
