"""Driver submit fast path (SCALE_r08): spec templates, batched
framing, and the shm submit ring.

Covers the PR's equivalence contracts:
- template-patched bytes == fresh pickle for every field combination
  (and out-of-domain calls decline to classic construction);
- a lease dying mid-batch fails exactly the specs in that batch — no
  strand, no double-run;
- GCS-path batch frames preserve FIFO order vs single-spec frames;
- ring-submitted specs execute identically to socket-submitted ones,
  ring-full falls back to the socket batch path, and a dead consumer's
  unconsumed records are recovered and resubmitted.
"""

import os
import pickle
import time

import pytest

import ray_tpu
from ray_tpu._private import protocol, spec_template
from ray_tpu._private.config import config
from ray_tpu._private.ids import JobID, ObjectID, PlacementGroupID, TaskID
from ray_tpu._private.task_spec import TaskSpec


# ----------------------------------------------------------- template unit

def _const(job, **over):
    base = dict(job_id=job, function_key="fn:0123456789abcdef", arg_deps=[],
                num_returns=1, resources={"CPU": 1.0}, name="nop",
                max_retries=3, retries_left=0, caller_id="client-1",
                owner_node="node-1", scheduling_strategy=None,
                placement_group_id=None, placement_group_bundle_index=-1,
                runtime_env=None, donate_result=False, trace_ctx=None)
    base.update(over)
    return base


FIELD_COMBOS = [
    {},
    {"num_returns": 0},
    {"num_returns": 3},
    {"num_returns": "dynamic"},
    {"resources": {"CPU": 2.0, "impossible": 1.0, "memory": 1e9}},
    {"name": ""},
    {"name": "a-much-longer-task-name-" * 8},
    {"max_retries": 0},
    {"donate_result": True},
    {"scheduling_strategy": "SPREAD"},
    {"placement_group_id": PlacementGroupID.of(JobID.from_int(7)),
     "placement_group_bundle_index": 2},
    {"caller_id": "", "owner_node": None},
]

ARGS_VALUES = [b"", b"x" * 10, b"y" * 255, b"z" * 256, os.urandom(4096)]


@pytest.mark.parametrize("combo", range(len(FIELD_COMBOS)))
def test_template_byte_equal_field_matrix(combo):
    """Template-patched bytes must equal pickle.dumps of an
    equivalently constructed spec, for every field combination and
    args sizes spanning the SHORT_BINBYTES/BINBYTES opcode boundary."""
    job = JobID.from_int(3)
    const = _const(job, **FIELD_COMBOS[combo])
    tpl = spec_template.build(const)
    assert tpl is not None
    for args in ARGS_VALUES:
        tid = TaskID.for_task(job)
        t = time.time()
        assert tpl.accepts(args, [], None)
        spec = tpl.make(tid, args, t)
        fresh = TaskSpec(task_id=tid, args=args, submitted_at=t, **const)
        want = pickle.dumps(fresh, protocol=5)
        assert spec_template.spec_wire(spec) == want
        # The decoded spec is field-for-field the fresh one.
        rt = pickle.loads(spec_template.spec_wire(spec))
        for f in TaskSpec._STATE_FIELDS:
            assert getattr(rt, f) == getattr(fresh, f), f


def test_template_declines_out_of_domain():
    job = JobID.from_int(3)
    tpl = spec_template.build(_const(job))
    assert tpl is not None
    # Dep-carrying, traced, spilled-args, and frame-breaking calls all
    # decline (classic construction covers them).
    assert not tpl.accepts(b"", [ObjectID.for_return(
        TaskID.for_task(job), 0)], None)
    assert not tpl.accepts(b"", [], {"trace_id": 1, "parent_span_id": 2})
    assert not tpl.accepts(("ref", b"\x00" * 28), [], None)
    assert not tpl.accepts(b"b" * (64 * 1024), [], None)


def test_template_verify_mode_catches_drift():
    """submit_template_verify re-checks every patched blob against a
    fresh pickle; a template whose frozen constants no longer match
    must raise, not ship wrong bytes."""
    job = JobID.from_int(3)
    tpl = spec_template.build(_const(job))
    tpl.set_verify(True)
    tpl.make(TaskID.for_task(job), b"ok", time.time())   # clean: passes
    tpl._const["name"] = "drifted"   # simulate constant drift
    with pytest.raises(AssertionError):
        tpl.make(TaskID.for_task(job), b"ok", time.time())


def test_wire_cache_invalidation():
    job = JobID.from_int(3)
    tpl = spec_template.build(_const(job))
    spec = tpl.make(TaskID.for_task(job), b"", time.time())
    assert spec.__dict__.get("_wire") is not None
    spec.max_retries = 1   # retry-path mutation
    spec_template.invalidate_wire(spec)
    assert spec.__dict__.get("_wire") is None
    # spec_wire now re-pickles the mutated spec.
    assert pickle.loads(spec_template.spec_wire(spec)).max_retries == 1


# -------------------------------------------------------- protocol framing

def test_notify_carries_no_msg_id_and_batches_deliver_in_order():
    """Notifies skip id allocation (msg_id 0 on the wire) and a burst of
    queued frames drains through the gathered write in order."""
    got = []
    import threading
    ev = threading.Event()

    def handler(conn, mtype, payload, msg_id):
        got.append((mtype, payload, msg_id))
        if len(got) >= 201:
            ev.set()

    srv = protocol.Server(handler, name="t-batch")
    conn = protocol.connect(srv.address, name="t-batch-c")
    try:
        for i in range(200):
            conn.notify("n", i)
        # A request after the burst: replies still match their future.
        fut = conn.request_nowait("n", "last")
        assert ev.wait(10)
        assert [p for _m, p, _i in got] == list(range(200)) + ["last"]
        assert all(i == 0 for _m, _p, i in got[:200])
        fut2 = conn.request_nowait("n", None)
        assert fut2.msg_id != 0
    finally:
        conn.close()
        srv.close()


# ------------------------------------------------------------ cluster glue

@pytest.fixture
def cluster():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker()


def _gcs():
    from ray_tpu._private import worker as worker_mod

    return worker_mod._global_cluster.gcs


def _nm():
    from ray_tpu._private import worker as worker_mod

    return worker_mod._global_cluster.nm


def _exported_spec(w, fn_key, name, max_retries=0, resources=None):
    args_blob, _deps = w._serialize_args((), {})
    return TaskSpec(
        task_id=TaskID.for_task(w.job_id), job_id=w.job_id,
        function_key=fn_key, args=args_blob, arg_deps=[], num_returns=1,
        resources=resources or {"CPU": 1.0}, name=name,
        max_retries=max_retries, caller_id=w.client_id,
        owner_node=w.node_id)


def test_remote_uses_template_and_matches_classic(cluster):
    """The RemoteFunction holder builds a template on first eligible
    call, and results are identical with the template path off."""
    @ray_tpu.remote
    def double(x):
        return 2 * x

    assert ray_tpu.get([double.remote(i) for i in range(20)]) == \
        [2 * i for i in range(20)]
    assert double._submit_template.tpl is not None

    old = config.submit_spec_template_enabled
    config.set("submit_spec_template_enabled", False)
    try:
        assert ray_tpu.get([double.remote(i) for i in range(20)]) == \
            [2 * i for i in range(20)]
    finally:
        config.set("submit_spec_template_enabled", old)


def test_gcs_batch_preserves_fifo_vs_single_frames(cluster):
    """Interleaved single-spec and batch frames on one conn land in the
    GCS shape queue in exact submission order."""
    w = _worker()
    gcs = _gcs()
    shape = {"CPU": 1.0, "impossible": 1.0}
    order = []
    conn = protocol.connect(w.gcs_address, name="t-fifo")
    try:
        for i in range(30):
            spec = _exported_spec(w, "fk", f"t{i}", resources=shape)
            order.append(f"t{i}")
            if i % 3 == 0:
                conn.notify("submit_task", spec)
            else:
                conn.notify("submit_task_batch",
                            [pickle.dumps(spec, protocol=5)])
        deadline = time.time() + 15
        while time.time() < deadline:
            names = [s.name for _k, q in gcs._queued_tasks.buckets()
                     for s in q if s.name.startswith("t")]
            if len(names) >= 30:
                break
            time.sleep(0.05)
        assert names == order
    finally:
        conn.close()


def test_gcs_batch_dedups_on_task_id(cluster):
    """At-least-once ring delivery: a spec arriving twice through the
    batch handler is enqueued once."""
    w = _worker()
    gcs = _gcs()
    spec = _exported_spec(w, "fk", "dup-probe",
                          resources={"CPU": 1.0, "impossible": 1.0})
    blob = pickle.dumps(spec, protocol=5)
    conn = protocol.connect(w.gcs_address, name="t-dedup")
    try:
        conn.notify("submit_task_batch", [blob])
        conn.notify("submit_task_batch", [blob])
        deadline = time.time() + 10
        count = 0
        while time.time() < deadline:
            count = sum(1 for _k, q in gcs._queued_tasks.buckets()
                        for s in q if s.name == "dup-probe")
            if count:
                time.sleep(0.5)   # let a duplicate land if it would
                count = sum(1 for _k, q in gcs._queued_tasks.buckets()
                            for s in q if s.name == "dup-probe")
                break
            time.sleep(0.05)
        assert count == 1
    finally:
        conn.close()


def test_lease_death_mid_batch_fails_exactly_that_batch(cluster,
                                                        tmp_path):
    """A transport failure on a batch send fails the specs of THAT
    batch only: zero-retry specs materialize WorkerCrashedError, specs
    with budget fall back and run EXACTLY once, and queued-but-unsent
    specs are not stranded."""
    import cloudpickle

    from ray_tpu._private import lease as lease_mod
    from ray_tpu._private.worker import ObjectRef
    from ray_tpu import exceptions as exc

    w = _worker()
    lm = w._lease_mgr
    marker = str(tmp_path / "runs.txt")

    def tracked(marker=marker):
        with open(marker, "a") as f:
            f.write("ran\n")
        return 99

    fn_key = w.export_function(cloudpickle.dumps(tracked))

    class BoomConn:
        closed = False

        def notify(self, *a, **k):
            raise protocol.ConnectionClosed()

        def close(self):
            pass

    key = (("CPU", 1.0),)
    lease = lease_mod._Lease(b"lid-t", b"wid-t", BoomConn(), w.node_id,
                             None, key, local=True)
    doomed = [_exported_spec(w, fn_key, "doomed-0"),
              _exported_spec(w, fn_key, "doomed-1")]
    retryable = _exported_spec(w, fn_key, "retry-1", max_retries=1)
    queued = _exported_spec(w, fn_key, "queued-1")
    with lm._lock:
        st = lm._shapes.get(key)
        assert st is not None or True
        if st is None:
            st = lm._shapes[key] = lease_mod._ShapeState()
        st.leases.append(lease)
        for s in doomed + [retryable]:
            lm._reserve_locked(lease, s)
        st.queue.append(queued)
    lm._send(lease, doomed + [retryable])

    # Zero-retry specs fail with WorkerCrashedError, not re-execution.
    for s in doomed:
        ref = ObjectRef(s.return_ids()[0])
        with pytest.raises(exc.WorkerCrashedError):
            ray_tpu.get(ref, timeout=30)
    # The budgeted spec and the queued spec run exactly once each.
    assert ray_tpu.get(ObjectRef(retryable.return_ids()[0]),
                       timeout=30) == 99
    assert ray_tpu.get(ObjectRef(queued.return_ids()[0]), timeout=30) == 99
    with open(marker) as f:
        assert len(f.read().splitlines()) == 2


# ------------------------------------------------------------- submit ring

def _force_ring(lm, timeout=10.0):
    lm.submit_classic(_exported_spec(
        _worker(), "fk", "ring-warm",
        resources={"CPU": 1.0, "impossible": 1.0}))
    deadline = time.time() + timeout
    while time.time() < deadline and lm._ring_state in (0, 1):
        time.sleep(0.05)
    return lm._ring


def test_ring_submitted_specs_execute_identically(cluster):
    """Specs shipped through the shm ring run to the same results as
    socket-submitted ones (and with the ring off, the same entry point
    uses the socket batch path)."""
    import cloudpickle

    from ray_tpu._private.worker import ObjectRef

    w = _worker()
    lm = w._lease_mgr

    def triple(x=3):
        return 3 * x

    fn_key = w.export_function(cloudpickle.dumps(triple))
    ring = _force_ring(lm)
    assert ring is not None and ring.active
    tail0 = ring._tail
    specs = [_exported_spec(w, fn_key, f"ring-{i}") for i in range(8)]
    for s in specs:
        assert lm.submit_classic(s)
    assert ring._tail > tail0   # they really rode the ring
    got = ray_tpu.get([ObjectRef(s.return_ids()[0]) for s in specs],
                      timeout=60)
    assert got == [9] * 8

    # Off-toggle: same entry point, socket path, same results.
    old = config.submit_ring_enabled
    lm2_ring_enabled = lm._ring_enabled
    lm._ring_enabled = False
    try:
        tail_before = ring._tail
        specs2 = [_exported_spec(w, fn_key, f"sock-{i}") for i in range(4)]
        for s in specs2:
            assert lm.submit_classic(s)
        lm.flush_sends()
        got2 = ray_tpu.get([ObjectRef(s.return_ids()[0]) for s in specs2],
                           timeout=60)
        assert got2 == [9] * 4
        assert ring._tail == tail_before  # untouched by the off path
    finally:
        lm._ring_enabled = lm2_ring_enabled
        config.set("submit_ring_enabled", old)


def test_ring_full_falls_back_to_socket(cluster):
    """Appends beyond capacity decline; the submission still lands via
    the socket batch path and the ring-full counter moves."""
    from ray_tpu._private import lease as lease_mod
    from ray_tpu._private.worker import ObjectRef
    import cloudpickle

    w = _worker()
    lm = w._lease_mgr
    old_bytes = config.submit_ring_bytes
    config.set("submit_ring_bytes", 16384)   # tiny: fills in ~80 records
    try:
        ring = _force_ring(lm)
        assert ring is not None

        def one():
            return 1

        fn_key = w.export_function(cloudpickle.dumps(one))
        # Stop the NM's drain thread so the ring can actually fill.
        nm = _nm()
        ents = [e for ents in nm._submit_rings.values() for e in ents]
        assert ents
        for e in ents:
            e["stop"] = True
        time.sleep(0.3)
        m = lease_mod._submit_metrics_get()
        full_before = sum(v for _n, _t, v in m[2].samples())
        # Fill with VALID spec blobs (one identity: the GCS dedups the
        # eventual recovery resubmission down to a single enqueue).
        filler = pickle.dumps(_exported_spec(
            w, "fk", "filler",
            resources={"CPU": 1.0, "impossible": 1.0}), protocol=5)
        n_fit = 0
        while ring.append(filler):
            n_fit += 1
            assert n_fit < 100_000
        assert n_fit > 0
        # Ring full: a real submission falls back to the socket path.
        spec = _exported_spec(w, fn_key, "spilled")
        assert lm.submit_classic(spec)
        lm.flush_sends()
        assert ray_tpu.get(ObjectRef(spec.return_ids()[0]),
                           timeout=60) == 1
        full_after = sum(v for _n, _t, v in m[2].samples())
        assert full_after >= full_before + 1
    finally:
        config.set("submit_ring_bytes", old_bytes)


def test_ring_consumer_death_recovers_unconsumed(cluster):
    """NM-side drain death: the driver notices the stale heartbeat,
    recovers unconsumed records, and resubmits them over the socket —
    the tasks still run."""
    import cloudpickle

    from ray_tpu._private.worker import ObjectRef

    w = _worker()
    lm = w._lease_mgr
    ring = _force_ring(lm)
    assert ring is not None

    def four():
        return 4

    fn_key = w.export_function(cloudpickle.dumps(four))
    nm = _nm()
    ents = [e for ents in nm._submit_rings.values() for e in ents]
    assert ents
    for e in ents:
        e["stop"] = True
    time.sleep(0.3)
    specs = [_exported_spec(w, fn_key, f"orphan-{i}") for i in range(5)]
    for s in specs:
        assert lm.submit_classic(s)
    assert ring._tail > 0
    # The flush loop detects the stale consumer within ~_RING_STALE_S
    # and resubmits; the records then execute.
    got = ray_tpu.get([ObjectRef(s.return_ids()[0]) for s in specs],
                      timeout=60)
    assert got == [4] * 5
    assert lm._ring is None and lm._ring_state == 3


def test_ring_disabled_never_registers(cluster):
    lm = _worker()._lease_mgr
    old = lm._ring_enabled
    lm._ring_enabled = False
    try:
        lm.submit_classic(_exported_spec(
            _worker(), "fk", "noring",
            resources={"CPU": 1.0, "impossible": 1.0}))
        time.sleep(0.2)
        assert lm._ring is None
    finally:
        lm._ring_enabled = old


def test_closure_captured_remote_function_after_template_build(cluster):
    """A RemoteFunction whose template is already BUILT (holder
    referencing this process's CoreWorker) must still cloudpickle into
    a worker via closure capture — the holder ships fresh."""
    @ray_tpu.remote
    def inner(x):
        return x * 2

    # Build inner's template in the driver first.
    assert ray_tpu.get([inner.remote(i) for i in range(4)]) == \
        [0, 2, 4, 6]
    assert inner._submit_template.tpl is not None

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(5), timeout=60) == 11


# ------------------------------------------------------- refcount batching

def test_incref_many_batches_under_one_lock():
    class _StubGcs:
        def __init__(self):
            self.sent = []

        def notify(self, mtype, payload):
            self.sent.append((mtype, payload))

    class _StubWorker:
        client_id = "stub"

        def __init__(self):
            self.gcs = _StubGcs()

    from ray_tpu._private.worker import _RefTracker

    tr = _RefTracker(_StubWorker())
    try:
        tr.incref_many([b"a", b"a", b"b"])
        tr.decref_many([b"b", b"c"])
        tr.flush()
        merged = {}
        for mtype, payload in tr._worker.gcs.sent:
            assert mtype == "update_refcounts"
            for oid, d in payload["deltas"].items():
                merged[oid] = merged.get(oid, 0) + d
        # Net-zero deltas still ship (they create the GCS count entry).
        assert merged == {b"a": 2, b"b": 0, b"c": -1}
        assert not tr._inc_log and not tr._dec_log
    finally:
        tr.stop()
