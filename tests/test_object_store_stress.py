"""Multi-process stress of the native shm arena (``store.cpp``):
create/seal/get/evict/delete races with clients SIGKILLed mid-operation.

The arena's index lives in shared memory behind one process-shared
ROBUST mutex; a client killed while holding it must leave the store
usable for every survivor (EOWNERDEAD -> ``pthread_mutex_consistent``,
store.cpp:110). The r5 shutdown segfault was found by luck — this is
the dedicated torture test (VERDICT r5 "What's weak" #6).
"""

import multiprocessing
import os
import random
import signal
import time

from ray_tpu.object_store import plasma

_POOL = 48              # shared object-id space => maximum contention
_CAPACITY = 1024 * 1024  # small arena => constant eviction pressure


def _oid(i: int) -> bytes:
    return b"ST" + i.to_bytes(4, "little") + b"\x00" * 22


def _hammer(path: str, seed: int):
    """Loop create/seal/get/release/delete over a shared oid pool until
    killed. Every op may race with a sibling's op on the same object."""
    rng = random.Random(seed)
    c = plasma.PlasmaClient(path)
    while True:
        o = _oid(rng.randrange(_POOL))
        r = rng.random()
        try:
            if r < 0.45:
                buf = c.create(o, rng.randrange(256, 48 * 1024))
                buf[:4] = b"data"
                del buf
                c.seal(o)
            elif r < 0.80:
                v = c.get_buffer(o, timeout_ms=0)
                if v is not None:
                    assert bytes(v[:4]) == b"data"
                    del v
                    c.release(o)
            else:
                c.delete(o)
        except plasma.ObjectExistsError:
            pass
        except plasma.StoreFullError:
            time.sleep(0.001)   # all pinned; let eviction catch up
        except Exception:
            pass   # racing delete/evict of the object mid-op


def _verify(path: str, q):
    """Full create/seal/get/delete round trip on a fresh client — run in
    a subprocess so a wedged arena mutex shows up as a join timeout, not
    a hung test suite."""
    try:
        c = plasma.PlasmaClient(path)
        o = _oid(_POOL + 7)   # outside the hammered pool
        c.delete(o)
        buf = c.create(o, 11)
        buf[:] = b"still-alive"
        del buf
        c.seal(o)
        v = c.get_buffer(o, timeout_ms=2000)
        ok = v is not None and bytes(v) == b"still-alive"
        if v is not None:
            del v
            c.release(o)
        c.delete(o)
        s = c.stats()
        ok = ok and 0 <= s["used_bytes"] <= s["capacity_bytes"]
        c.close()
        q.put(("ok" if ok else f"bad state: {s}", s))
    except BaseException as e:
        q.put((f"error: {e!r}", None))


def test_store_survives_client_sigkill(tmp_path):
    path = str(tmp_path / "stress-arena")
    plasma.create_store(path, capacity=_CAPACITY, max_objects=256)
    ctx = multiprocessing.get_context("fork")
    rng = random.Random(0xC0FFEE)
    stats = None
    for round_no in range(3):
        procs = [ctx.Process(target=_hammer,
                             args=(path, round_no * 10 + i), daemon=True)
                 for i in range(4)]
        for p in procs:
            p.start()
        time.sleep(0.4)   # let contention build
        for p in procs:
            time.sleep(rng.uniform(0.0, 0.15))   # land kills mid-op
            os.kill(p.pid, signal.SIGKILL)
        for p in procs:
            p.join(timeout=10)
            assert not p.is_alive()
        q = ctx.Queue()
        v = ctx.Process(target=_verify, args=(path, q), daemon=True)
        v.start()
        v.join(timeout=20)
        if v.is_alive():
            v.kill()
            raise AssertionError(
                f"round {round_no}: verifier hung — arena mutex not "
                f"recovered after client SIGKILL")
        status, stats = q.get(timeout=5)
        assert status == "ok", f"round {round_no}: {status}"
    # The pressure was real: the eviction path ran under the races.
    assert stats is not None and stats["evictions"] > 0
