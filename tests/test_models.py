"""Model-zoo tests: shapes, loss decrease, sharded-vs-local parity."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ray_tpu.models import (
    GPTConfig, init_params, param_logical_axes, forward, loss_fn,
    make_train_state, make_train_step, count_params,
    MLPConfig, mlp_init, mlp_forward,
)
from ray_tpu.parallel import make_mesh


def _mesh(axes):
    import math
    n = math.prod(axes.values())
    return make_mesh(axes=axes, devices=jax.devices()[:n])


def _batch(rng, cfg, b=2, l=16):
    toks = jax.random.randint(rng, (b, l + 1), 0, cfg.vocab_size)
    return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def test_forward_shapes():
    cfg = GPTConfig.preset("tiny")
    params = init_params(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), cfg)
    logits = forward(params, batch["inputs"], cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert jnp.isfinite(logits).all()
    # logical-axes tree matches the params tree structure
    axes = param_logical_axes(cfg)
    jax.tree.map(lambda p, a: None, params, axes,
                 is_leaf=lambda x: x is None or isinstance(x, tuple))


def test_param_count_gpt2_125m():
    cfg = GPTConfig.preset("gpt2-125m")
    params = jax.eval_shape(lambda: init_params(jax.random.key(0), cfg))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    assert 120e6 < n < 135e6  # ~124M + vocab padding


def test_causality():
    """Future tokens must not influence earlier logits."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (1, 16), 0, cfg.vocab_size)
    base = forward(params, toks, cfg)
    perturbed = toks.at[0, -1].set((toks[0, -1] + 1) % cfg.vocab_size)
    out = forward(params, perturbed, cfg)
    np.testing.assert_allclose(base[0, :-1], out[0, :-1], atol=1e-5)
    assert not np.allclose(base[0, -1], out[0, -1])


def test_rotary_matches_shapes():
    cfg = GPTConfig.preset("tiny", rotary=True)
    params = init_params(jax.random.key(0), cfg)
    assert "pos_embed" not in params
    batch = _batch(jax.random.key(1), cfg)
    assert forward(params, batch["inputs"], cfg).shape == (
        2, 16, cfg.vocab_size)


def test_training_reduces_loss():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, remat=False)
    opt = optax.adamw(1e-3)
    state = make_train_state(jax.random.key(0), cfg, opt)
    step = jax.jit(make_train_step(cfg, opt))
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    _, first = step(state, batch)
    for _ in range(10):
        state, metrics = step(state, batch)
    assert metrics["loss"] < first["loss"]
    assert jnp.isfinite(metrics["grad_norm"])


@pytest.mark.parametrize("axes", [
    {"dp": 2}, {"fsdp": 2}, {"dp": 2, "tp": 2}, {"dp": 2, "sp": 2, "tp": 2},
])
def test_sharded_forward_parity(axes):
    """Mesh-sharded forward == single-device forward."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    params = init_params(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    local = forward(params, batch["inputs"], cfg)

    mesh = _mesh(axes)
    from ray_tpu.parallel.sharding import shard_pytree
    sp = shard_pytree(params, mesh, param_logical_axes(cfg))
    sharded = jax.jit(
        lambda p, t: forward(p, t, cfg, mesh=mesh))(sp, batch["inputs"])
    np.testing.assert_allclose(np.asarray(local), np.asarray(sharded),
                               atol=2e-4)


def test_ring_attention_model_parity():
    """ring_attention=True over an sp mesh == plain attention."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    cfg_ring = GPTConfig.preset("tiny", dtype=jnp.float32,
                                ring_attention=True)
    params = init_params(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), cfg, b=2, l=32)
    mesh = _mesh({"sp": 4})
    local = forward(params, batch["inputs"], cfg)
    ring = jax.jit(
        lambda p, t: forward(p, t, cfg_ring, mesh=mesh))(
            params, batch["inputs"])
    np.testing.assert_allclose(np.asarray(local), np.asarray(ring),
                               atol=2e-4)


def test_sharded_train_step_runs():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32)
    mesh = _mesh({"dp": 2, "fsdp": 2, "tp": 2})
    opt = optax.adamw(1e-3)
    state = make_train_state(jax.random.key(0), cfg, opt, mesh=mesh)
    step = jax.jit(make_train_step(cfg, opt, mesh=mesh), donate_argnums=0)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    state, metrics = step(state, batch)
    assert int(state.step) == 1
    assert jnp.isfinite(metrics["loss"])


def test_mlp():
    cfg = MLPConfig()
    params = mlp_init(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (8, 784))
    assert mlp_forward(params, x).shape == (8, 10)


def test_count_params():
    cfg = GPTConfig.preset("tiny")
    assert count_params(init_params(jax.random.key(0), cfg)) > 0


def test_moe_forward_and_training():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, remat=False,
                           moe_experts=4, moe_capacity_factor=2.0)
    params = init_params(jax.random.key(0), cfg)
    assert params["blocks"]["w_up"].shape == (2, 4, 64, 256)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    logits = forward(params, batch["inputs"], cfg)
    assert logits.shape == (4, 32, cfg.vocab_size)
    assert jnp.isfinite(logits).all()

    import optax
    opt = optax.adamw(1e-3)
    state = make_train_state(jax.random.key(0), cfg, opt)
    step = jax.jit(make_train_step(cfg, opt))
    _, first = step(state, batch)
    for _ in range(8):
        state, m = step(state, batch)
    assert m["loss"] < first["loss"]


def test_moe_sharded_parity():
    """ep-sharded MoE == single-device MoE."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32,
                           moe_experts=4, moe_capacity_factor=2.0)
    params = init_params(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    local = forward(params, batch["inputs"], cfg)

    mesh = _mesh({"dp": 2, "ep": 4})
    from ray_tpu.parallel.sharding import shard_pytree
    sp = shard_pytree(params, mesh, param_logical_axes(cfg))
    sharded = jax.jit(
        lambda p, t: forward(p, t, cfg, mesh=mesh))(sp, batch["inputs"])
    np.testing.assert_allclose(np.asarray(local), np.asarray(sharded),
                               atol=2e-4)


def test_pipeline_parity():
    """pp=2 pipelined forward == sequential forward."""
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, remat=False)
    params = init_params(jax.random.key(0), cfg)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    local = forward(params, batch["inputs"], cfg)

    mesh = _mesh({"pp": 2})
    piped = jax.jit(
        lambda p, t: forward(p, t, cfg, mesh=mesh))(params,
                                                    batch["inputs"])
    np.testing.assert_allclose(np.asarray(local), np.asarray(piped),
                               atol=2e-4)


def test_pipeline_training_step():
    """Full train step over a dp x pp mesh (grads through ppermute)."""
    import optax
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, remat=False)
    mesh = _mesh({"dp": 2, "pp": 2})
    opt = optax.adamw(1e-2)
    state = make_train_state(jax.random.key(0), cfg, opt, mesh=mesh)
    step = jax.jit(make_train_step(cfg, opt, mesh=mesh), donate_argnums=0)
    batch = _batch(jax.random.key(1), cfg, b=4, l=32)
    losses = []
    for _ in range(6):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_flash_attention_model_parity():
    cfg = GPTConfig.preset("tiny", dtype=jnp.float32, max_seq=128)
    cfg_flash = GPTConfig.preset("tiny", dtype=jnp.float32, max_seq=128,
                                 flash_attention=True)
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 128), 0,
                              cfg.vocab_size)
    base = forward(params, toks, cfg)
    flash = forward(params, toks, cfg_flash)
    np.testing.assert_allclose(np.asarray(base), np.asarray(flash),
                               atol=2e-4)
