"""map_batches(compute=ActorPoolStrategy): stateful UDFs on a pool of
long-lived actors (reference: python/ray/data/_internal/compute.py
ActorPoolStrategy) — the TPU batch-inference pattern: load a model once
per actor, stream blocks through it."""

import os

import numpy as np
import pytest

import ray_tpu
import ray_tpu.data as rd
from ray_tpu.data import ActorPoolStrategy


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_actor_pool_init_once_per_actor(ray_cluster, tmp_path):
    """A JAX-model UDF class: __init__ (model build) runs once per pool
    actor, NOT once per block."""
    marker = str(tmp_path / "inits.txt")

    class JaxPredictor:
        def __init__(self, path):
            import jax
            import jax.numpy as jnp

            with open(path, "a") as f:
                f.write(f"{os.getpid()}\n")
            k = jax.random.key(0)
            self.w = jax.random.normal(k, (4, 2))
            self.apply = jax.jit(lambda w, x: jnp.tanh(x @ w))

        def __call__(self, batch):
            out = np.asarray(self.apply(self.w, batch["x"]))
            return {"y": out}

    ds = rd.from_items([{"x": np.ones(4, np.float32) * i}
                        for i in range(32)]).map_batches(
        JaxPredictor, batch_size=4,
        compute=ActorPoolStrategy(min_size=1, max_size=2),
        fn_constructor_args=(marker,))
    rows = ds.take_all()
    assert len(rows) == 32
    assert all(r["y"].shape == (2,) for r in rows)
    inits = open(marker).read().splitlines()
    # 32 rows / batch 4 = 8 batches over >=4 blocks, but at most
    # max_size=2 constructions (one per actor).
    assert 1 <= len(inits) <= 2, inits
    assert len(set(inits)) == len(inits)   # distinct actor processes


def test_actor_pool_respects_max_size(ray_cluster):
    class PidUdf:
        def __call__(self, batch):
            return {"pid": np.full(len(batch["v"]), os.getpid())}

    ds = rd.from_items([{"v": i} for i in range(40)]).map_batches(
        PidUdf, batch_size=5, compute=ActorPoolStrategy(min_size=1,
                                                        max_size=2))
    pids = {int(p) for r in ds.take_all() for p in [r["pid"]]}
    assert 1 <= len(pids) <= 2, pids


def test_actor_pool_composes_with_task_stages(ray_cluster):
    """Task stages fuse around the actor barrier: map -> actor-pool
    map_batches -> filter, with exact results in order."""
    class AddTen:
        def __call__(self, batch):
            return {"v": batch["v"] + 10}

    ds = (rd.range(20, parallelism=4)
          .map(lambda x: {"v": x})
          .map_batches(AddTen, compute="actors")
          .filter(lambda r: r["v"] % 2 == 0))
    got = sorted(int(r["v"]) for r in ds.take_all())
    assert got == [v + 10 for v in range(20) if (v + 10) % 2 == 0]


def test_actor_pool_explain_and_plain_callable(ray_cluster):
    ds = rd.range(8, parallelism=2).map(lambda x: {"v": x}).map_batches(
        lambda b: {"v": b["v"] * 2},
        compute=ActorPoolStrategy(min_size=1, max_size=3))
    text = ds.explain()
    assert "ActorPool" in text and "max=3" in text
    assert sorted(int(r["v"]) for r in ds.take_all()) == \
        [2 * v for v in range(8)]


def test_actor_pool_streaming_iter_batches(ray_cluster):
    class Ident:
        def __call__(self, batch):
            return batch

    ds = rd.range(24, parallelism=6).map(lambda x: {"v": x}).map_batches(
        Ident, compute="actors")
    seen = [int(v) for b in ds.iter_batches(batch_size=8)
            for v in b["v"]]
    assert sorted(seen) == list(range(24))
