"""Tests for ray_tpu.parallel: mesh construction, sharding rules,
collective ops (xla + store backends) on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ray_tpu.parallel import (
    MeshConfig,
    make_mesh,
    topology_info,
    AxisRules,
    DEFAULT_RULES,
    shard_pytree,
    collective,
)
from ray_tpu.parallel.collective import ReduceOp


# ------------------------------------------------------------------- mesh


def test_mesh_config_resolve():
    cfg = MeshConfig(dp=-1, tp=2).resolve(8)
    assert cfg.dp == 4 and cfg.tp == 2
    with pytest.raises(ValueError):
        MeshConfig(dp=3, tp=2).resolve(8)
    with pytest.raises(ValueError):
        MeshConfig(dp=-1, tp=-1).resolve(8)


def test_make_mesh_drops_trivial_axes():
    mesh = make_mesh(axes={"dp": 4, "tp": 2})
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.devices.shape == (4, 2)
    mesh2 = make_mesh(axes={"dp": 8, "tp": 1})
    assert mesh2.axis_names == ("dp",)


def test_make_mesh_keep_trivial():
    mesh = make_mesh(axes={"dp": 8}, keep_trivial=True)
    assert mesh.axis_names == ("dp", "fsdp", "pp", "ep", "sp", "tp")
    assert mesh.devices.shape == (8, 1, 1, 1, 1, 1)


def test_topology_info():
    info = topology_info()
    assert info["num_devices"] == 8
    assert info["num_hosts"] == 1


# --------------------------------------------------------------- sharding


def test_axis_rules_spec_and_sharding():
    rules = AxisRules(batch=("dp", "fsdp"), embed="fsdp", mlp="tp")
    mesh = make_mesh(axes={"dp": 2, "fsdp": 2, "tp": 2})
    sh = rules.sharding(mesh, "batch", None, "mlp")
    from jax.sharding import PartitionSpec as P

    assert sh.spec == P(("dp", "fsdp"), None, "tp")
    # Rules naming absent mesh axes degrade to replication on that dim.
    mesh_dp = make_mesh(axes={"dp": 8})
    sh2 = rules.sharding(mesh_dp, "batch", "mlp")
    assert sh2.spec == P("dp", None)


def test_shard_pytree():
    mesh = make_mesh(axes={"dp": 4, "tp": 2})
    tree = {"w": np.ones((8, 4), np.float32), "b": np.zeros((4,), np.float32)}
    axes = {"w": ("batch", "mlp"), "b": None}
    rules = AxisRules(batch="dp", mlp="tp")
    out = shard_pytree(tree, mesh, axes, rules)
    assert out["w"].sharding.spec == jax.sharding.PartitionSpec("dp", "tp")
    np.testing.assert_array_equal(np.asarray(out["w"]), tree["w"])


# ------------------------------------------------------------- collectives


@pytest.fixture
def xla_group():
    g = collective.init_collective_group(
        world_size=8, rank=0, backend="xla", group_name="test_xla")
    yield g
    collective.destroy_collective_group("test_xla")


def test_xla_allreduce(xla_group):
    tensors = [np.full((4,), float(i)) for i in range(8)]
    out = xla_group.allreduce(tensors)
    expected = np.full((4,), float(sum(range(8))))
    for o in out:
        np.testing.assert_allclose(np.asarray(o), expected)


def test_xla_allreduce_ops(xla_group):
    tensors = [np.full((2, 2), float(i + 1)) for i in range(8)]
    out_max = xla_group.allreduce(tensors, op=ReduceOp.MAX)
    np.testing.assert_allclose(np.asarray(out_max[0]), 8.0)
    out_min = xla_group.allreduce(tensors, op=ReduceOp.MIN)
    np.testing.assert_allclose(np.asarray(out_min[3]), 1.0)
    out_avg = xla_group.allreduce(tensors, op=ReduceOp.AVG)
    np.testing.assert_allclose(np.asarray(out_avg[0]), 4.5)


def test_xla_allgather(xla_group):
    tensors = [np.full((3,), float(i)) for i in range(8)]
    out = xla_group.allgather(tensors)
    # Every rank gets all shards, in rank order.
    expected = np.repeat(np.arange(8.0), 3)
    for o in out:
        np.testing.assert_allclose(np.asarray(o).reshape(-1), expected)


def test_xla_reducescatter(xla_group):
    tensors = [np.arange(8.0) for _ in range(8)]
    out = xla_group.reducescatter(tensors)
    for r, o in enumerate(out):
        np.testing.assert_allclose(np.asarray(o).ravel(), [8.0 * r])


def test_xla_broadcast(xla_group):
    tensors = [np.full((2,), float(i)) for i in range(8)]
    out = xla_group.broadcast(tensors, src_rank=3)
    for o in out:
        np.testing.assert_allclose(np.asarray(o), 3.0)


def test_xla_permute_ring(xla_group):
    perm = [(i, (i + 1) % 8) for i in range(8)]
    tensors = [np.full((1,), float(i)) for i in range(8)]
    out = xla_group.permute(tensors, perm)
    for i in range(8):
        np.testing.assert_allclose(np.asarray(out[(i + 1) % 8]), float(i))


def test_module_level_api():
    collective.init_collective_group(4, 0, backend="xla", group_name="mod")
    try:
        assert collective.is_group_initialized("mod")
        assert collective.get_rank("mod") == 0
        assert collective.get_collective_group_size("mod") == 4
        out = collective.allreduce(
            [np.ones(2) for _ in range(4)], group_name="mod")
        np.testing.assert_allclose(np.asarray(out[0]), 4.0)
    finally:
        collective.destroy_collective_group("mod")
    assert not collective.is_group_initialized("mod")


# store backend needs a running cluster
def _store_worker(rank, world, results):
    g = collective.StoreGroup(world, rank, "store_test")
    r = g.allreduce(np.full((4,), float(rank + 1)))
    ag = g.allgather(np.full((2,), float(rank)))
    rs = g.reducescatter(np.arange(float(world * 2)))
    bc = g.broadcast(np.full((2,), 7.0) if rank == 1 else None, src_rank=1)
    g.barrier()
    results[rank] = (r, ag, rs, bc)


def test_store_backend_collectives(ray_start_regular):
    import threading

    world = 3
    results = {}
    threads = [
        threading.Thread(target=_store_worker, args=(r, world, results))
        for r in range(world)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == world
    for rank in range(world):
        r, ag, rs, bc = results[rank]
        np.testing.assert_allclose(r, 6.0)  # 1+2+3
        np.testing.assert_allclose(ag, np.stack(
            [np.full((2,), float(i)) for i in range(world)]))
        chunk = 2
        np.testing.assert_allclose(
            rs, 3.0 * np.arange(float(world * 2))[rank * chunk:(rank + 1) * chunk])
        np.testing.assert_allclose(bc, 7.0)


def test_store_send_recv(ray_start_regular):
    import threading

    out = {}

    def sender():
        g = collective.StoreGroup(2, 0, "p2p_test")
        g.send(np.arange(6.0).reshape(2, 3), dst_rank=1)

    def receiver():
        g = collective.StoreGroup(2, 1, "p2p_test")
        out["v"] = g.recv((2, 3), np.float64, src_rank=0)

    ts = [threading.Thread(target=sender), threading.Thread(target=receiver)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    np.testing.assert_allclose(out["v"], np.arange(6.0).reshape(2, 3))
