"""Multi-node Cluster harness + autoscaler tests (reference:
``tests/test_autoscaler_fake_multinode.py`` and cluster_utils tests)."""

import time

import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod
from ray_tpu.autoscaler import (
    AutoscalerConfig, FakeMultiNodeProvider, NodeType, StandardAutoscaler,
)
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def cluster():
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 1})
    ctx = c.connect(ignore_reinit_error=True)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_multinode_scheduling(cluster):
    """Tasks spill to a second node when the head is saturated."""
    cluster.add_node(num_cpus=2, resources={"special": 1.0})
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"special": 1.0})
    def where():
        import ray_tpu
        return ray_tpu.get_runtime_context().get_node_id()

    node_id = ray_tpu.get(where.remote())
    special_node = cluster.nodes[1]
    assert node_id == special_node.node_id


def test_remove_node_fails_tasks_over(cluster):
    node = cluster.add_node(num_cpus=2, resources={"doomed": 1.0})
    assert cluster.wait_for_nodes()

    @ray_tpu.remote(resources={"doomed": 0.5}, max_retries=0)
    def stuck():
        import time
        time.sleep(60)

    ref = stuck.remote()
    time.sleep(1.0)
    cluster.remove_node(node)
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=20)


def test_autoscaler_scales_up_and_down(cluster):
    provider = FakeMultiNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types=[NodeType("cpu_worker", {"CPU": 2.0}, max_workers=3)],
        max_workers=3, idle_timeout_s=1.5)
    core = worker_mod.require_worker()
    scaler = StandardAutoscaler(core.gcs, provider, config)

    # Saturate: head has 1 CPU; ask for 4 CPUs worth of long tasks.
    @ray_tpu.remote(num_cpus=1)
    def hold(t):
        import time
        time.sleep(t)
        return 1

    refs = [hold.remote(6) for _ in range(4)]
    time.sleep(0.5)
    summary = scaler.run_once()
    assert summary["launched"] >= 2, summary
    assert cluster.wait_for_nodes()

    # With new nodes, all tasks complete.
    assert ray_tpu.get(refs, timeout=60) == [1, 1, 1, 1]

    # After idle_timeout the fake nodes are terminated.
    deadline = time.time() + 30
    while time.time() < deadline:
        scaler.run_once()
        if not provider.non_terminated_nodes():
            break
        time.sleep(0.5)
    assert not provider.non_terminated_nodes()


def test_autoscaler_respects_max_workers(cluster):
    provider = FakeMultiNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types=[NodeType("cpu_worker", {"CPU": 1.0}, max_workers=2)],
        max_workers=2, idle_timeout_s=60)
    core = worker_mod.require_worker()
    scaler = StandardAutoscaler(core.gcs, provider, config)

    @ray_tpu.remote(num_cpus=1)
    def hold():
        import time
        time.sleep(5)

    _refs = [hold.remote() for _ in range(10)]
    time.sleep(0.5)
    scaler.run_once()
    scaler.run_once()
    assert len(provider.non_terminated_nodes()) <= 2


def test_min_workers_launched(cluster):
    provider = FakeMultiNodeProvider(cluster)
    config = AutoscalerConfig(
        node_types=[NodeType("warm", {"CPU": 1.0}, min_workers=2,
                             max_workers=4)],
        max_workers=4, idle_timeout_s=60)
    core = worker_mod.require_worker()
    scaler = StandardAutoscaler(core.gcs, provider, config)
    summary = scaler.run_once()
    assert summary["launched"] == 2
    assert len(provider.non_terminated_nodes()) == 2


def test_pg_prefers_single_slice_for_tpu_bundles():
    """TPU placement groups pack onto one ICI slice: bundles must not
    straddle slice labels when a single slice can host the gang
    (SURVEY hard part (f))."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.placement_group import placement_group

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1})
    # Two 2-host slices, each host with 4 chips.
    nodes = {}
    for sl in ("slice-a", "slice-b"):
        for h in range(2):
            nm = cluster.add_node(num_cpus=2, num_tpus=4,
                                  labels={"slice": sl})
            nodes[nm.node_id] = sl
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    try:
        # 2 bundles x 4 TPU: exactly one slice's worth, spread over hosts.
        pg = placement_group([{"TPU": 4, "CPU": 1}] * 2,
                             strategy="STRICT_SPREAD")
        assert pg.wait(timeout_seconds=30)
        from ray_tpu._private import worker as worker_mod

        table = worker_mod.require_worker().gcs.request("pg_table", {})
        bundles = table[pg.id.binary()]["bundles"]
        placed_slices = {nodes[b["node_id"]] for b in bundles}
        assert len(placed_slices) == 1, (
            f"gang straddles slices: {placed_slices}")
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
