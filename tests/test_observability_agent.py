"""Per-node observability agent: cluster-wide log/stack fan-in, the
flight recorder, and reporter/metrics lifecycle (reference:
dashboard/agent.py + reporter/log modules beside every raylet).

The two load-bearing scenarios (ISSUE 8 acceptance):
- a blocked collective rank's Python stack is retrievable cluster-wide
  through the in-band `ray_tpu stack` path — bounded, no SIGUSR2;
- a gang death leaves a flight-recorder dump on disk containing the
  dead rank's last task events/spans.
"""

import json
import glob
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.experimental import state
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import config


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _session_dir() -> str:
    return worker_mod._global_cluster.session_dir


def _flight_dir() -> str:
    return os.path.join(_session_dir(), "flight_recorder")


def _wait_for(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {msg}")


# ------------------------------------------------------------- stacks


def test_wedged_collective_rank_stack_capture(ray_cluster):
    """ISSUE 8 wedge test: one collective rank blocked in an allreduce
    (its peer never joins the op) is diagnosable cluster-wide via the
    in-band stack path — bounded, no SIGUSR2, no log scraping."""

    @ray_tpu.remote
    class Rank:
        def __init__(self, rank):
            self.rank = rank

        def join(self, world):
            from ray_tpu.parallel import collective

            collective.init_collective_group(
                world, self.rank, backend="store", group_name="wedge_g")
            return True

        def reduce(self):
            import numpy as np

            from ray_tpu.parallel import collective

            return collective.allreduce(
                np.ones(4), group_name="wedge_g").tolist()

    r0, r1 = Rank.remote(0), Rank.remote(1)
    assert ray_tpu.get([r0.join.remote(2), r1.join.remote(2)],
                       timeout=60) == [True, True]
    wedged_ref = r0.reduce.remote()   # rank 1 never calls reduce
    time.sleep(1.5)                   # let rank 0 enter the op

    t0 = time.time()
    nodes = state.dump_stacks(timeout_s=5)
    assert time.time() - t0 < 20      # bounded capture
    assert nodes and nodes[0].get("node_id")
    # The wedged rank's main thread shows the collective frames.
    wedged = [w for n in nodes for w in n.get("workers", [])
              if any("_exchange" in t["stack"] or "allreduce" in t["stack"]
                     for t in w.get("threads", []))]
    assert wedged, json.dumps(nodes)[:2000]
    assert wedged[0]["actor_id"] == r0._actor_id.hex()
    # The CLI renderer shows the same frames as text.
    from ray_tpu.scripts.cli import format_stack_report

    report = format_stack_report(nodes)
    assert "_exchange" in report or "allreduce" in report
    assert "=== node" in report and "--- worker" in report

    # Unwedge and clean up: poison raises GangMemberDiedError promptly.
    from ray_tpu import exceptions
    from ray_tpu.parallel import collective

    collective.poison_group("wedge_g", "test teardown")
    with pytest.raises((exceptions.GangMemberDiedError,
                        exceptions.RayTaskError, Exception)):
        ray_tpu.get(wedged_ref, timeout=30)


def test_stack_capture_includes_node_manager_threads(ray_cluster):
    nodes = state.dump_stacks(timeout_s=5)
    nm = nodes[0]["node_manager"]
    assert nm["pid"] == os.getpid()   # head NM is in-process here
    names = {t["thread_name"] for t in nm["threads"]}
    assert any(n.startswith("rtpu-nm-") for n in names), names


# --------------------------------------------------------------- logs


def test_worker_log_fan_in(ray_cluster):
    @ray_tpu.remote
    def chatty():
        print("OBS_MARKER_fan_in")
        return 1

    assert ray_tpu.get(chatty.remote(), timeout=30) == 1

    def marker_seen():
        entries = state.get_log(lines=200)
        return any("OBS_MARKER_fan_in" in ln
                   for e in entries for ln in e.get("lines", []))

    _wait_for(marker_seen, 15, "log marker through the agent fan-in")

    # Listing mode enumerates the node's workers with their streams.
    listing = state.list_logs()
    assert listing and listing[0]["workers"]
    assert all({"worker_id", "alive", "streams"} <= set(w)
               for w in listing[0]["workers"])

    # Prefix filtering by worker id narrows to that worker only.
    entries = state.get_log(lines=200)
    target = next(e for e in entries
                  if any("OBS_MARKER_fan_in" in ln for ln in e["lines"]))
    only = state.get_log(ident=target["worker_id"][:12], lines=200)
    assert only and all(e["worker_id"] == target["worker_id"]
                        for e in only)


def test_actor_log_fan_in_routes_by_actor_id(ray_cluster):
    @ray_tpu.remote
    class Talker:
        def say(self):
            print("OBS_MARKER_actor_log")
            return True

    a = Talker.remote()
    assert ray_tpu.get(a.say.remote(), timeout=30)
    aid = a._actor_id.hex()

    def seen():
        entries = state.get_log(actor_id=aid, lines=200)
        return any("OBS_MARKER_actor_log" in ln
                   for e in entries for ln in e.get("lines", []))

    _wait_for(seen, 15, "actor log lines through the agent")
    entries = state.get_log(actor_id=aid, lines=200)
    assert all(e["actor_id"] == aid for e in entries)


def test_dead_workers_logs_reachable_by_actor_and_full_id(ray_cluster):
    """Postmortem lookup: after an actor's worker dies, its log files
    must stay reachable by actor id and FULL worker id (the agent keeps
    an identity index outliving the NM's worker table)."""
    @ray_tpu.remote
    class Doomed:
        def say(self):
            print("OBS_MARKER_dead_actor")
            return True

    a = Doomed.remote()
    assert ray_tpu.get(a.say.remote(), timeout=30)
    aid = a._actor_id.hex()

    def entries_for(**kw):
        return [e for e in state.get_log(lines=200, **kw)
                if any("OBS_MARKER_dead_actor" in ln
                       for ln in e.get("lines", []))]

    _wait_for(lambda: entries_for(actor_id=aid), 15,
              "actor logs before death")
    wid_full = entries_for(actor_id=aid)[0]["worker_id"]
    assert len(wid_full) > 12

    ray_tpu.kill(a)
    # Once the worker leaves the NM table the row is rebuilt from the
    # on-disk filename + identity index; both query shapes must hold.
    _wait_for(lambda: any(not e.get("alive", True)
                          for e in state.get_log(actor_id=aid,
                                                 lines=1) or [{}])
              or entries_for(actor_id=aid), 15, "post-death rows")
    deadline = time.time() + 15
    while time.time() < deadline:
        by_actor = entries_for(actor_id=aid)
        by_full_wid = entries_for(worker_id=wid_full)
        if by_actor and by_full_wid:
            break
        time.sleep(0.3)
    assert by_actor, "dead actor's logs unreachable by actor id"
    assert by_full_wid, "dead worker's logs unreachable by full id"
    assert by_actor[0]["actor_id"] == aid


# ----------------------------------------------------- flight recorder


def test_flight_recorder_dump_on_gang_death():
    """ISSUE 8 acceptance: a gang death leaves a flight-recorder dump on
    disk containing the dead rank's last task events."""
    old = config.get("gang_heartbeat_s")
    config.set("gang_heartbeat_s", 0.5)
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    from ray_tpu.train.worker_group import WorkerGroup

    group = None
    try:
        group = WorkerGroup(2, {"CPU": 1}, backend="store",
                            group_name="frgang", experiment_name="fr")
        dead_actor = group.workers[1]._actor_id.hex()
        # Give the workers' 0.2 s event flush a beat so the recorder
        # holds their setup_collective task events before the kill.
        time.sleep(1.0)
        ray_tpu.kill(group.workers[1])

        pattern = os.path.join(_flight_dir(), "flight-*.json")
        _wait_for(lambda: glob.glob(pattern), 20,
                  "a flight-recorder dump after gang death")
        # Newest dump (worker-death and supervisor triggers may both
        # fire; the supervisor's carries the gang reason).
        dumps = [json.load(open(p)) for p in sorted(glob.glob(pattern))]
        assert any("frgang" in (d.get("reason") or "")
                   or "rank 1" in (d.get("reason") or "")
                   or "died" in (d.get("reason") or "") for d in dumps)
        events = [e for d in dumps for e in d["events"]]
        # The dead rank's last task events made it into the artifact...
        assert any(e.get("name") == "setup_collective" for e in events)
        # ...and its worker's death is recorded against its actor id.
        assert any(e.get("kind") == "worker_death"
                   and e.get("actor_id") == dead_actor for e in events)
        # Metric snapshots ride the same ring.
        assert any(e.get("kind") == "hw_sample" for e in events)
    finally:
        if group is not None:
            group.shutdown(graceful=False)
        ray_tpu.shutdown()
        config.set("gang_heartbeat_s", old)


def test_flight_recorder_dump_on_unexpected_worker_death(ray_cluster):
    @ray_tpu.remote(max_retries=0)
    def suicide():
        import os as _os
        import signal as _signal

        _os.kill(_os.getpid(), _signal.SIGKILL)

    with pytest.raises(Exception):
        ray_tpu.get(suicide.remote(), timeout=30)
    pattern = os.path.join(_flight_dir(), "flight-*.json")
    _wait_for(lambda: glob.glob(pattern), 15,
              "a dump after an unexpected worker death")
    dump = json.load(open(sorted(glob.glob(pattern))[-1]))
    assert "died unexpectedly" in dump["reason"]
    assert any(e.get("kind") == "worker_death" for e in dump["events"])


def test_flight_snapshot_over_node_agent_endpoint(ray_cluster):
    """The agent endpoint is directly addressable on the node's
    existing transport (no new server stack)."""
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(3)], timeout=30)
    w = worker_mod.require_worker()
    addr = w.nodes()[0]["NodeManagerAddress"]

    def kinds():
        snap = w.nm_conn(addr).request("flight_snapshot", {},
                                       timeout=10)
        return {e.get("kind") for e in snap["events"]}

    # Worker event flush is 0.2 s; the hw sample rides the next 1 s
    # heartbeat tick — poll rather than guess the interleaving.
    _wait_for(lambda: {"task", "hw_sample"} <= kinds(), 15,
              "task events + hw samples in the flight ring")


# ------------------------------------------- reporter/metrics lifecycle


def _reporter_threads():
    return [t for t in threading.enumerate() if t.name == "rtpu-metrics"]


def test_metrics_reporter_idempotent_and_joined_on_shutdown():
    """ISSUE 8 satellite + acceptance: repeated start_reporter calls
    share one thread, and ray_tpu.shutdown() joins it — init/shutdown
    cycles must not stack reporter threads."""
    from ray_tpu.util import metrics

    for _ in range(2):
        ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
        try:
            t1 = metrics.start_reporter(period_s=0.2)
            t2 = metrics.start_reporter(period_s=5.0)
            t3 = metrics.start_reporter()
            assert t1 is t2 is t3
            assert len(_reporter_threads()) == 1
        finally:
            ray_tpu.shutdown()
        _wait_for(lambda: not _reporter_threads(), 5,
                  "reporter thread to be joined on shutdown")
    assert not _reporter_threads()


def test_metrics_drop_dead_client_series(ray_cluster):
    """A downscaled/killed replica's gauges must leave /metrics within
    3 reporting periods (or immediately once the GCS knows the client
    is gone)."""
    from ray_tpu.util import metrics

    w = worker_mod.require_worker()
    # A series from a client the GCS has no connection for (a killed
    # replica): dropped on the next read.
    w.gcs.notify("report_metrics", {
        "client_id": "worker-deadbeef", "ts": time.time(),
        "period_s": 2.0,
        "samples": [{"name": "serve_llm_queue_depth",
                     "tags": {"replica": "deadbeef"}, "value": 9.0,
                     "kind": "gauge", "help": "stale"}]})
    # The live driver's series stays.
    g = metrics.Gauge("obs_live_gauge", "x")
    g.set(1.0)
    assert metrics.report_to_gcs()

    def flat():
        return [s for grp in w.gcs.request("get_metrics") for s in grp]

    _wait_for(lambda: any(s["name"] == "obs_live_gauge"
                          for s in flat()), 10, "live gauge visible")
    assert not any(s["name"] == "serve_llm_queue_depth"
                   and s["tags"].get("replica") == "deadbeef"
                   for s in flat())

    # Time-based expiry: a connected-but-silent client's series drop
    # after missing ≥3 of its own reporting periods.
    w.gcs.notify("report_metrics", {
        "client_id": w.client_id + ":probe", "ts": time.time(),
        "period_s": 0.1,
        "samples": [{"name": "obs_silent_gauge", "tags": {},
                     "value": 2.0, "kind": "gauge", "help": ""}]})
    # (unknown client id: dropped for both reasons — assert it goes)
    _wait_for(lambda: not any(s["name"] == "obs_silent_gauge"
                              for s in flat()), 10,
              "silent client's series to expire")


def test_report_to_gcs_logs_failures_once_per_kind(caplog):
    """The reporter must not swallow failures silently (raylint
    exception-swallow triage): one warning per failure kind."""
    import logging

    from ray_tpu.util import metrics

    class _BoomGcs:
        def notify(self, *a, **k):
            raise ConnectionResetError("boom")

    class _FakeWorker:
        gcs = _BoomGcs()
        client_id = "fake"

    old_worker = worker_mod._global_worker
    metrics._report_failures_logged.clear()
    worker_mod._global_worker = _FakeWorker()
    try:
        with caplog.at_level(logging.WARNING, logger="ray_tpu.metrics"):
            assert metrics.report_to_gcs() is False
            assert metrics.report_to_gcs() is False
    finally:
        worker_mod._global_worker = old_worker
    warnings = [r for r in caplog.records
                if "metrics report" in r.getMessage()]
    assert len(warnings) == 1          # once per failure kind
    assert "ConnectionResetError" in warnings[0].getMessage()
