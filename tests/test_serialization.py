import numpy as np

from ray_tpu._private import serialization as ser


def test_roundtrip_simple():
    for v in [1, "x", None, {"a": [1, 2, (3, 4)]}, b"bytes"]:
        assert ser.loads_oob(ser.dumps_oob(v)) == v


def test_numpy_out_of_band_zero_copy():
    arr = np.arange(1 << 16, dtype=np.float32)
    sobj = ser.serialize({"w": arr, "tag": "x"})
    # The array must have gone out-of-band, not into the pickle stream.
    assert len(sobj.metadata) < arr.nbytes // 2
    assert sum(b.nbytes for b in sobj.buffers) >= arr.nbytes
    back = ser.loads_oob(sobj.to_bytes())
    np.testing.assert_array_equal(back["w"], arr)


def test_zero_copy_view_shares_memory():
    arr = np.arange(1024, dtype=np.int64)
    data = ser.dumps_oob(arr)
    view = memoryview(bytearray(data))
    back = ser.deserialize_framed(view)
    np.testing.assert_array_equal(back, arr)
    # Mutating the backing view must show through (proves zero-copy).
    back2 = ser.deserialize_framed(view)
    view_arr = np.frombuffer(view, dtype=np.int64,
                             count=1024, offset=data.index(arr[:8].tobytes()))
    view_arr[0] = 999
    assert back2[0] == 999


def test_alignment():
    arr = np.ones(100, dtype=np.float64)
    sobj = ser.serialize(arr)
    data = sobj.to_bytes()
    back = ser.loads_oob(data)
    # 64-byte alignment lets numpy map the buffer without copying.
    np.testing.assert_array_equal(back, arr)


def test_function_roundtrip():
    def f(x):
        return x * 2

    g = ser.loads_oob(ser.dumps_oob(f))
    assert g(21) == 42


def test_exception_roundtrip():
    from ray_tpu.exceptions import RayTaskError

    try:
        raise ValueError("boom")
    except ValueError as e:
        err = RayTaskError.from_exception("f", e)
    back = ser.loads_oob(ser.dumps_oob(err))
    assert isinstance(back, RayTaskError)
    assert "boom" in back.traceback_str
    assert isinstance(back.as_instanceof_cause(), ValueError)
