"""Actor tests (modelled on the reference's python/ray/tests/test_actor.py)."""

import time

import pytest

import ray_tpu


def test_basic_actor(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote()) == 1
    assert ray_tpu.get(c.incr.remote()) == 2


def test_actor_constructor_args(ray_start_regular):
    @ray_tpu.remote
    class A:
        def __init__(self, a, b=10):
            self.v = a + b

        def get(self):
            return self.v

    assert ray_tpu.get(A.remote(1).get.remote()) == 11
    assert ray_tpu.get(A.remote(1, b=2).get.remote()) == 3


def test_actor_ordering(ray_start_regular):
    @ray_tpu.remote
    class Appender:
        def __init__(self):
            self.log = []

        def add(self, x):
            self.log.append(x)

        def get(self):
            return self.log

    a = Appender.remote()
    for i in range(20):
        a.add.remote(i)
    assert ray_tpu.get(a.get.remote()) == list(range(20))


def test_actor_state_isolated(ray_start_regular):
    @ray_tpu.remote
    class C:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c1, c2 = C.remote(), C.remote()
    ray_tpu.get(c1.incr.remote())
    assert ray_tpu.get(c2.incr.remote()) == 1


def test_actor_error(ray_start_regular):
    @ray_tpu.remote
    class A:
        def fail(self):
            raise RuntimeError("method failed")

        def ok(self):
            return "fine"

    a = A.remote()
    with pytest.raises(RuntimeError, match="method failed"):
        ray_tpu.get(a.fail.remote())
    # actor survives method errors
    assert ray_tpu.get(a.ok.remote()) == "fine"


def test_actor_creation_error(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise ValueError("ctor failed")

        def m(self):
            return 1

    b = Bad.remote()
    with pytest.raises(Exception):
        ray_tpu.get(b.m.remote(), timeout=20)


def test_named_actor(ray_start_regular):
    @ray_tpu.remote
    class Store:
        def __init__(self):
            self.v = {}

        def put(self, k, v):
            self.v[k] = v

        def get(self, k):
            return self.v.get(k)

    s = Store.options(name="kvstore").remote()
    ray_tpu.get(s.put.remote("a", 1))
    handle = ray_tpu.get_actor("kvstore")
    assert ray_tpu.get(handle.get.remote("a")) == 1
    with pytest.raises(ValueError):
        ray_tpu.get_actor("missing")


def test_duplicate_named_actor(ray_start_regular):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    A.options(name="dup").remote()
    time.sleep(0.1)
    with pytest.raises(Exception):
        h = A.options(name="dup").remote()
        ray_tpu.get(h.m.remote(), timeout=10)


def test_pass_handle_to_task(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    @ray_tpu.remote
    def bump(counter):
        return ray_tpu.get(counter.incr.remote())

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c)) == 1
    assert ray_tpu.get(c.incr.remote()) == 2


def test_kill_actor(ray_start_regular):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    a = A.remote()
    assert ray_tpu.get(a.m.remote()) == 1
    ray_tpu.kill(a)
    time.sleep(0.3)
    with pytest.raises(ray_tpu.exceptions.RayActorError):
        ray_tpu.get(a.m.remote(), timeout=10)


def test_actor_restart(ray_start_regular):
    @ray_tpu.remote(max_restarts=1)
    class Flaky:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def die(self):
            import os
            os._exit(1)

    f = Flaky.remote()
    assert ray_tpu.get(f.incr.remote()) == 1
    f.die.remote()
    time.sleep(1.0)
    # restarted: state reset
    assert ray_tpu.get(f.incr.remote(), timeout=30) == 1


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class AsyncWorker:
        async def work(self, t):
            import asyncio
            await asyncio.sleep(t)
            return t

    w = AsyncWorker.remote()
    ray_tpu.get(w.work.remote(0.0))  # warm: actor alive, route cached
    t0 = time.time()
    refs = [w.work.remote(0.3) for _ in range(5)]
    assert ray_tpu.get(refs, timeout=30) == [0.3] * 5
    # concurrent: should take ~0.3s, not 1.5s
    assert time.time() - t0 < 1.2


def test_threaded_actor(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self, t):
            time.sleep(t)
            return t

    s = Sleeper.remote()
    ray_tpu.get(s.nap.remote(0.0))  # warm: actor alive, route cached
    t0 = time.time()
    ray_tpu.get([s.nap.remote(0.4) for _ in range(4)], timeout=30)
    assert time.time() - t0 < 1.3


def test_exit_actor(ray_start_regular):
    @ray_tpu.remote
    class Quitter:
        def quit(self):
            from ray_tpu.actor import exit_actor
            exit_actor()

        def m(self):
            return 1

    q = Quitter.remote()
    assert ray_tpu.get(q.m.remote()) == 1
    ray_tpu.get(q.quit.remote(), timeout=10)
    time.sleep(0.5)
    with pytest.raises(ray_tpu.exceptions.RayActorError):
        ray_tpu.get(q.m.remote(), timeout=10)


def test_actor_method_num_returns(ray_start_regular):
    @ray_tpu.remote
    class M:
        @ray_tpu.method(num_returns=2)
        def two(self):
            return 1, 2

    m = M.remote()
    a, b = m.two.remote()
    assert ray_tpu.get([a, b]) == [1, 2]


def test_actor_pool(ray_start_regular):
    from ray_tpu.util import ActorPool

    @ray_tpu.remote
    class Doubler:
        def double(self, x):
            return 2 * x

    pool = ActorPool([Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(6)))
    assert out == [0, 2, 4, 6, 8, 10]
