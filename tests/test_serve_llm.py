"""Disaggregated LLM serving tests: continuous batching engine behind
serve, prefill->decode KV handoff over device objects (zero host
materializations same-process), streaming responses through the handle,
queue-depth autoscaling, and the pushed-stats handle routing."""

import time

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.config import AutoscalingConfig
from ray_tpu.serve.llm import EngineConfig, build_llm_app
from ray_tpu.serve.llm.replicas import _build_model

ENGINE_CONFIG = dict(
    preset="tiny", model_overrides={"dtype": "float32"},
    max_slots=4, max_len=64, prompt_buckets=(16,), max_new_tokens=16)

PROMPT = [5, 9, 2, 11, 3]
N = 8


@pytest.fixture(scope="module")
def serve_cluster():
    ctx = ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    serve.start(http_port=None)
    yield ctx
    serve.shutdown()
    ray_tpu.shutdown()


@pytest.fixture(scope="module")
def ref_tokens():
    """generate()'s greedy output for PROMPT — the parity oracle every
    serving path must reproduce."""
    cfg, params = _build_model(EngineConfig.from_dict(ENGINE_CONFIG))
    out = generate_ref(cfg, params)
    return out


def generate_ref(cfg, params):
    from ray_tpu.models.generate import generate

    return [int(x) for x in generate(
        params, jnp.asarray([PROMPT], jnp.int32), jax.random.key(0),
        cfg=cfg, max_new_tokens=N, temperature=0.0)[0]]


def test_kv_handoff_same_process_zero_host_materializations(serve_cluster):
    """Prefill -> publish -> adopt -> decode entirely in this process:
    the KV blocks come back BY REFERENCE from the per-CoreWorker
    weak-value cache (device-object probe: local hits, zero host
    materializations, zero arena rebuilds) and decoding off the adopted
    blocks reproduces generate()."""
    from ray_tpu._private import device_objects
    from ray_tpu.models.generate import (
        adopt_slot, decode_step, init_slotted_cache, prefill_slot,
    )
    from ray_tpu.serve.llm.kv_transfer import adopt_kv, publish_kv

    ec = EngineConfig.from_dict(ENGINE_CONFIG)
    cfg, params = _build_model(ec)
    ref = generate_ref(cfg, params)

    padded = jnp.zeros((1, 16), jnp.int32).at[:, :len(PROMPT)].set(
        jnp.asarray(PROMPT, jnp.int32))
    first, kv = prefill_slot(params, padded, jnp.int32(len(PROMPT)),
                             jnp.int32(0), cfg=cfg)
    jax.block_until_ready(kv)

    device_objects.reset_stats()
    handoff = publish_kv(kv, len(PROMPT), int(first[0]), n=N, seed=0)
    adopted = adopt_kv(handoff)
    s = device_objects.stats()
    assert s["host_materializations"] == 0, s
    assert s["local_hits"] == 2, s          # k and v, by reference
    assert s["rebuilds"] == 0, s            # never left HBM
    assert adopted["k"] is kv["k"] and adopted["v"] is kv["v"]

    # Decode off the adopted blocks: token-for-token with generate().
    cache = adopt_slot(init_slotted_cache(cfg, 2, ec.max_len),
                       jnp.int32(0), adopted, jnp.int32(len(PROMPT)))
    tokens = [handoff["first_token"]]
    last = jnp.zeros((2,), jnp.int32).at[0].set(handoff["first_token"])
    active = jnp.zeros((2,), bool).at[0].set(True)
    seeds = jnp.zeros((2,), jnp.int32)
    for _ in range(N - 1):
        nxt, cache = decode_step(params, cache, last, active, seeds,
                                 cfg=cfg)
        tokens.append(int(nxt[0]))
        last = last.at[0].set(nxt[0])
    assert tokens == ref


def test_disaggregated_app_end_to_end(serve_cluster, ref_tokens):
    """prefill pool -> KV handoff -> decode pool behind the /llm router,
    both the blocking and the streaming path."""
    handle = serve.run(build_llm_app(ENGINE_CONFIG, mode="disaggregated",
                                     name="llm"),
                       route_prefix="/llm")
    out = handle.remote({"prompt": PROMPT, "n": N}).result(timeout=300)
    assert out["tokens"] == ref_tokens

    chunks = list(handle.generate_stream.remote_gen(
        {"prompt": PROMPT, "n": N}))
    assert chunks[0] == [ref_tokens[0]]     # prefill's token arrives first
    assert [t for c in chunks for t in c] == ref_tokens
    serve.delete("llm")
    serve.delete("llm-prefill")
    serve.delete("llm-decode")


def test_combined_app_streaming_and_parity(serve_cluster, ref_tokens):
    handle = serve.run(build_llm_app(ENGINE_CONFIG, mode="combined",
                                     name="llmc"),
                       route_prefix="/llmc")
    out = handle.remote({"prompt": PROMPT, "n": N}).result(timeout=300)
    assert out["tokens"] == ref_tokens
    chunks = list(handle.generate_stream.remote_gen(
        {"prompt": PROMPT, "n": N}))
    flat = [t for c in chunks for t in c]
    assert flat == ref_tokens
    assert len(chunks) >= 2                 # streamed, not one blob
    serve.delete("llmc")
    serve.delete("llmc-engine")


def test_autoscale_up_then_down_on_engine_queue_depth(serve_cluster):
    """Flooding the engine queue drives autoscale_load (queue depth +
    busy slots) through the controller's queue-depth policy: the engine
    pool scales up under backlog and back down once drained."""
    handle = serve.run(
        build_llm_app(
            dict(ENGINE_CONFIG, max_slots=2),
            mode="combined", name="llma",
            autoscaling_config=AutoscalingConfig(
                min_replicas=1, max_replicas=2,
                target_ongoing_requests=6.0,
                upscale_delay_s=0.2, downscale_delay_s=1.0,
                look_back_period_s=1.0)),
        route_prefix="/llma")
    # Warm (compile) before flooding so the backlog is real decode work.
    handle.remote({"prompt": PROMPT, "n": 4}).result(timeout=300)

    pool = "llma-engine"
    assert serve.status()[pool]["num_replicas"] == 1
    responses = [handle.remote({"prompt": [1 + i % 50, 2, 3], "n": 16})
                 for i in range(80)]
    deadline = time.time() + 60
    peak = 1
    while time.time() < deadline:
        peak = max(peak, serve.status()[pool]["num_replicas"])
        if peak >= 2:
            break
        time.sleep(0.2)
    assert peak >= 2, "engine pool never scaled up under queue backlog"
    for r in responses:
        r.result(timeout=300)
    deadline = time.time() + 60
    while time.time() < deadline:
        if serve.status()[pool]["num_replicas"] == 1:
            break
        time.sleep(0.2)
    assert serve.status()[pool]["num_replicas"] == 1, \
        "engine pool never scaled back down after drain"
    serve.delete("llma")
    serve.delete(pool)


def test_handle_routes_on_pushed_stats_without_rpcs(serve_cluster):
    """The controller piggybacks per-replica load on the replicas
    long-poll channel; the handle's P2C reads pushed loads + local
    deltas — no stats RPCs on the hot path."""
    @serve.deployment(num_replicas=2, name="pushed")
    class Echo:
        def __call__(self, x):
            return x

    handle = serve.run(Echo.bind(), http_port=None)
    for i in range(4):
        assert handle.remote(i).result(timeout=30) == i

    # The listener must deliver a pushed load map (keyed by actor id).
    deadline = time.time() + 15
    while time.time() < deadline:
        with handle._lock:
            pushed = dict(handle._pushed_load)
        if pushed:
            break
        handle.remote(0).result(timeout=30)
        time.sleep(0.2)
    assert pushed, "no pushed per-replica loads arrived on the handle"
    # Let the trailing all-idle push land before pinning loads manually
    # (pushes only happen when the load map changes, so after this the
    # channel is quiet).
    time.sleep(1.0)
    with handle._lock:
        replicas = list(handle._replicas)
    aids = {r._actor_id.hex() for r in replicas}
    assert set(pushed) <= aids | set(pushed)  # keys are actor ids
    assert set(pushed) & aids

    # P2C on pushed loads: a replica marked heavily loaded is avoided.
    heavy, light = replicas[0], replicas[1]
    with handle._lock:
        handle._pushed_load = {heavy._actor_id.hex(): 100.0,
                               light._actor_id.hex(): 0.0}
        handle._local_delta.clear()
    picks = {handle._pick()._actor_id.hex() for _ in range(12)}
    assert picks == {light._actor_id.hex()}
    serve.delete("pushed")


def test_engine_failure_propagates_not_wedges(serve_cluster):
    """A bad request (prompt beyond every bucket) fails ITS caller and
    leaves the engine serving others."""
    handle = serve.run(build_llm_app(ENGINE_CONFIG, mode="combined",
                                     name="llmf"),
                       route_prefix="/llmf")
    with pytest.raises(Exception, match="bucket"):
        handle.remote({"prompt": list(range(40)), "n": 4}).result(
            timeout=120)
    out = handle.remote({"prompt": PROMPT, "n": 4}).result(timeout=120)
    assert len(out["tokens"]) == 4
    serve.delete("llmf")
    serve.delete("llmf-engine")
