"""OOM monitor: worker RSS + store usage sampling and the retriable-
first worker-killing policy (reference: memory_monitor.h:52,
worker_killing_policy.h:34)."""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def tight_memory_cluster():
    """Cluster whose memory budget is ~250 MiB above the current worker
    baseline, so one 500 MiB allocation trips the monitor."""
    ctx = ray_tpu.init(
        num_cpus=2, object_store_memory=32 * 1024 * 1024,
        _system_config={
            "memory_monitor_refresh_ms": 100,
            # workers idle at ~60-120 MiB RSS each (jax imports); leave
            # room for that baseline but not for a 500 MiB hog.
            "memory_limit_bytes": 600 * 1024 * 1024,
            "memory_usage_threshold": 0.8,
        })
    yield ctx
    ray_tpu.shutdown()


def test_oom_hog_killed_node_survives(tight_memory_cluster):
    """A task allocating past the limit is killed (surfacing the OOM
    cause) instead of wedging the node; ordinary work keeps running."""

    @ray_tpu.remote(max_retries=0)
    def hog():
        ballast = np.ones(500 * 1024 * 1024 // 8, np.float64)
        time.sleep(30)
        return ballast.nbytes

    ref = hog.remote()
    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError) as ei:
        ray_tpu.get(ref, timeout=90)
    assert "memory monitor" in str(ei.value)

    @ray_tpu.remote
    def fine():
        return 42

    assert ray_tpu.get(fine.remote(), timeout=60) == 42


def test_oom_kill_is_retriable(tight_memory_cluster):
    """A retriable task killed by the monitor is retried; when it behaves
    on retry (allocation released), it completes."""
    import os

    marker = f"/tmp/rtpu_oom_marker_{os.getpid()}"

    @ray_tpu.remote(max_retries=2)
    def sometimes_hog():
        if not os.path.exists(marker):
            open(marker, "w").write("x")
            ballast = np.ones(500 * 1024 * 1024 // 8, np.float64)
            time.sleep(30)
            return int(ballast[0])
        return 7

    try:
        assert ray_tpu.get(sometimes_hog.remote(), timeout=120) == 7
    finally:
        import contextlib

        with contextlib.suppress(OSError):
            os.remove(marker)
