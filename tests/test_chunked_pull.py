"""Chunked cross-node object transfer with pull admission control.

Reference behaviors under test: 5 MiB transfer chunks
(src/ray/common/ray_config_def.h:332, object_manager.proto), bounded
in-flight pull quota (src/ray/object_manager/pull_manager.h:52), and
chunked restore of spilled objects. The memory assertion pins the point
of chunking: pulling an object must not buffer a second whole copy on
either side's heap.
"""

import resource
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu._private.config import config


@pytest.fixture
def two_node_small_chunks():
    # 256 KiB chunks so a few-MiB object exercises many chunks fast.
    config.set("fetch_chunk_bytes", 256 * 1024)
    config.set("pull_max_inflight_chunks", 4)
    cluster = Cluster(initialize_head=True, head_node_args={
        "num_cpus": 2, "object_store_memory": 256 * 1024 * 1024})
    cluster.add_node(num_cpus=2, object_store_memory=256 * 1024 * 1024)
    cluster.connect(object_store_memory=256 * 1024 * 1024)
    cluster.wait_for_nodes()
    yield cluster
    ray_tpu.shutdown()
    cluster.shutdown()
    config.set("fetch_chunk_bytes", 5 * 1024 * 1024)
    config.set("pull_max_inflight_chunks", 8)


def test_chunked_pull_roundtrip(two_node_small_chunks):
    """A multi-chunk object produced on the remote node arrives intact."""
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def make(seed):
        rng = np.random.default_rng(seed)
        return rng.integers(0, 255, 3 * 1024 * 1024, dtype=np.uint8)

    refs = [make.remote(s) for s in range(4)]
    vals = ray_tpu.get(refs, timeout=120)
    for s, v in zip(range(4), vals):
        expect = np.random.default_rng(s).integers(
            0, 255, 3 * 1024 * 1024, dtype=np.uint8)
        np.testing.assert_array_equal(v, expect)


def test_chunked_pull_bounded_memory(two_node_small_chunks):
    """Pulling a large object must not buffer a whole second copy on
    anyone's Python heap: peak heap growth during the pull stays at
    O(window * chunk), not O(object).

    tracemalloc is the right probe here because the test-process RSS
    includes BOTH in-process node managers' shm arenas (cluster_utils
    runs them in one process); the heap is where an unchunked transfer
    would buffer the 96 MiB blob twice (sender bytes() + receiver
    data), and that is exactly what chunking eliminates.
    """
    import tracemalloc

    size = 96 * 1024 * 1024

    @ray_tpu.remote(num_cpus=1)
    def make_big():
        return np.zeros(96 * 1024 * 1024, dtype=np.uint8)

    ref = make_big.remote()
    ray_tpu.wait([ref], timeout=120)
    tracemalloc.start()
    try:
        val = ray_tpu.get(ref, timeout=180)
        _cur, peak = tracemalloc.get_traced_memory()
    finally:
        tracemalloc.stop()
    assert val.nbytes == size
    # window(4) * chunk(256 KiB) = 1 MiB of transfer buffers; allow 16x
    # slack for unrelated allocations. An unchunked transfer would peak
    # at >= size (one whole-blob bytes copy on the serving side alone).
    assert peak < 16 * 1024 * 1024, f"heap peaked at {peak/1e6:.0f} MB"
    del val


def test_concurrent_pulls_do_not_blow_store(two_node_small_chunks):
    """8 concurrent multi-chunk pulls complete with a bounded shared
    admission window (no OOM, no deadlock)."""
    @ray_tpu.remote(num_cpus=1, scheduling_strategy="SPREAD")
    def chunk_blob(seed):
        return np.full(4 * 1024 * 1024, seed, dtype=np.uint8)

    refs = [chunk_blob.remote(s) for s in range(8)]
    vals = ray_tpu.get(refs, timeout=180)
    for s, v in zip(range(8), vals):
        assert v[0] == s and v[-1] == s and v.nbytes == 4 * 1024 * 1024


def test_chunked_restore_from_spill(two_node_small_chunks):
    """A spilled object on the holder node is served to a remote puller
    by range-reading spill storage (no whole-blob materialization)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker()
    # Put a multi-chunk object locally, spill it via its node manager,
    # then fetch it back through the chunk path pretending to be remote.
    blob = np.arange(2 * 1024 * 1024, dtype=np.uint8)
    ref = ray_tpu.put(blob)
    oid = ref.binary()
    cluster = two_node_small_chunks
    head = cluster.nodes[0]
    # Spill everything spillable on the head node.
    head._spill_bytes(1 << 30)
    if not head._spilled_url(oid):
        pytest.skip("object was not spilled (store pressure too low)")
    # Evict the in-memory copy so the fetch must hit spill storage.
    w.store.delete(oid)
    assert not w.store.contains(oid)
    addr = head.address
    assert w._fetch_from(addr, oid)
    got, ok = w.store.get_value(oid, timeout_ms=10_000)
    assert ok
    np.testing.assert_array_equal(got, blob)
