"""Chaos tests: SIGKILL workers and nodes mid-flight and assert the
failure paths (retries, actor restart, reroute, lineage, spill) hold.

Reference model: the chaos_* release tests +
``python/ray/_private/test_utils.py:1347`` (NodeKillerActor).
"""

import os
import signal
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import test_utils as tu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def local_cluster():
    """Single in-process head with real worker subprocesses."""
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    from ray_tpu._private import worker as worker_mod

    nm = worker_mod._global_cluster.nm
    yield nm
    ray_tpu.shutdown()


@pytest.fixture
def two_node():
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    other = cluster.add_node(num_cpus=2)
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    yield cluster, other
    ray_tpu.shutdown()
    cluster.shutdown()


def test_sigkill_worker_mid_task_retries(local_cluster):
    """A task whose worker is SIGKILLed mid-run retries and succeeds."""

    @ray_tpu.remote(max_retries=2)
    def slow_square(x):
        time.sleep(1.0)
        return x * x

    ref = slow_square.remote(7)
    pid = tu.kill_any_busy_worker(local_cluster)
    assert pid is not None, "no busy worker appeared to kill"
    assert ray_tpu.get(ref, timeout=60) == 49


def test_sigkill_worker_no_retries_raises(local_cluster):
    @ray_tpu.remote(max_retries=0)
    def hang():
        time.sleep(30)

    ref = hang.remote()
    pid = tu.kill_any_busy_worker(local_cluster)
    assert pid is not None
    with pytest.raises(ray_tpu.exceptions.WorkerCrashedError):
        ray_tpu.get(ref, timeout=60)


def test_sigkill_actor_process_restarts(local_cluster):
    @ray_tpu.remote(max_restarts=1)
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def pid(self):
            return os.getpid()

    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=30) == 1
    pid1 = ray_tpu.get(c.pid.remote(), timeout=30)
    os.kill(pid1, signal.SIGKILL)
    # The restarted instance answers with fresh state in a new process.
    deadline = time.time() + 60
    while True:
        try:
            n = ray_tpu.get(c.incr.remote(), timeout=30)
            break
        except ray_tpu.exceptions.RayActorError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)
    assert n == 1
    assert ray_tpu.get(c.pid.remote(), timeout=30) != pid1


def test_actor_task_ordering_across_restart(local_cluster):
    """Per-caller FIFO holds across an actor restart: the journal of a
    restarted actor is a contiguous 1..k prefix per incarnation, with no
    reordering inside an incarnation (reference:
    direct_actor_task_submitter.h sequencing + actor restart semantics)."""

    @ray_tpu.remote(max_restarts=1, max_task_retries=-1)
    class Journal:
        def __init__(self):
            self.log = []

        def append(self, i):
            self.log.append(i)
            return (os.getpid(), len(self.log), i)

        def pid(self):
            return os.getpid()

    j = Journal.remote()
    pid1 = ray_tpu.get(j.pid.remote(), timeout=30)
    refs = [j.append.remote(i) for i in range(20)]
    time.sleep(0.15)  # let a few land in the first incarnation
    os.kill(pid1, signal.SIGKILL)
    out = ray_tpu.get(refs, timeout=120)

    # Group by incarnation (pid); within each, the actor-local sequence
    # numbers must be contiguous from 1 and the submitted order preserved.
    by_pid = {}
    for pid, seq, i in out:
        by_pid.setdefault(pid, []).append((seq, i))
    assert len(by_pid) <= 2
    for pid, entries in by_pid.items():
        seqs = [s for s, _ in entries]
        assert seqs == sorted(seqs), "reordered within an incarnation"
        submitted = [i for _, i in entries]
        assert submitted == sorted(submitted), "caller FIFO violated"
    # Every call executed exactly once from the caller's perspective.
    assert sorted(i for _, _, i in out) == list(range(20))


def test_cross_node_fetch(two_node):
    """An object produced on node B is pulled to the driver's node."""
    cluster, other = two_node
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    @ray_tpu.remote
    def produce():
        return np.arange(1 << 18, dtype=np.uint8)

    ref = produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=other.node_id, soft=False)).remote()
    out = ray_tpu.get(ref, timeout=60)
    assert out.shape == (1 << 18,)
    # It was fetched into the driver's local store.
    from ray_tpu._private import worker as worker_mod

    assert worker_mod.require_worker().store.contains(ref.binary())


def test_node_kill_mid_task_reschedules(two_node):
    """Killing a node abruptly mid-task reschedules the task elsewhere."""
    cluster, other = two_node
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    @ray_tpu.remote(max_retries=2)
    def slow():
        time.sleep(1.0)
        return os.getpid()

    ref = slow.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=other.node_id, soft=True)).remote()
    time.sleep(0.3)  # task starts on `other`
    tu.kill_node(cluster, other)
    assert isinstance(ray_tpu.get(ref, timeout=60), int)


def test_node_kill_lineage_rebuild(two_node):
    """Abrupt node death + lost objects: lineage rebuilds on survivors."""
    cluster, other = two_node
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    @ray_tpu.remote(max_retries=2)
    def produce(seed):
        return np.full((1 << 15,), seed, np.uint8)

    refs = [produce.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(
            node_id=other.node_id, soft=False)).remote(i)
        for i in range(3)]
    vals = ray_tpu.get(refs, timeout=60)
    assert [int(v[0]) for v in vals] == [0, 1, 2]
    del vals
    tu.kill_node(cluster, other)
    rebuilt = ray_tpu.get(refs, timeout=60)
    assert [int(v[0]) for v in rebuilt] == [0, 1, 2]


def test_chaos_monkey_task_sweep(local_cluster):
    """A NodeKiller SIGKILLing busy workers every 300ms cannot lose any
    retriable task."""

    @ray_tpu.remote(max_retries=-1 if False else 5)
    def work(i):
        time.sleep(0.1)
        return i * 2

    killer = tu.NodeKiller([local_cluster], period_s=0.3).start()
    try:
        refs = [work.remote(i) for i in range(40)]
        out = ray_tpu.get(refs, timeout=180)
    finally:
        killer.stop()
    assert out == [i * 2 for i in range(40)]
    assert killer.kills, "chaos monkey never killed anything"


def test_spill_restore_under_churn(local_cluster):
    """Objects spilled under memory pressure restore correctly while new
    puts keep forcing eviction/spill."""
    rng = np.random.default_rng(0)
    blobs = [rng.integers(0, 255, 6 << 20, dtype=np.uint8)
             for _ in range(8)]  # 8 x 6MiB through a 128MiB store w/ churn
    refs = [ray_tpu.put(b) for b in blobs]
    # Churn: more puts to push earlier objects toward spill.
    churn = [ray_tpu.put(rng.integers(0, 255, 6 << 20, dtype=np.uint8))
             for _ in range(12)]
    for i, r in enumerate(refs):
        out = ray_tpu.get(r, timeout=60)
        np.testing.assert_array_equal(out, blobs[i])
    del churn
