"""Object spilling tests (reference: ``test_object_spilling*.py`` —
pressure-driven spill to disk, transparent restore on get)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.external_storage import FileSystemStorage


def test_filesystem_storage_roundtrip(tmp_path):
    st = FileSystemStorage(str(tmp_path / "spill"))
    url = st.spill(b"\x01" * 28, b"hello world")
    assert url.startswith("file://")
    assert st.restore(url) == b"hello world"
    st.delete(url)
    with pytest.raises(OSError):
        st.restore(url)


def test_spill_and_restore_under_pressure():
    # 8 MiB store; 6 x 2MiB objects overflow it well past the 0.8
    # threshold, forcing spills; every object must still be gettable.
    ray_tpu.init(num_cpus=2, object_store_memory=8 * 1024 * 1024)
    try:
        blobs = [np.full(2 * 1024 * 1024 // 8, i, np.int64)
                 for i in range(6)]
        refs = [ray_tpu.put(b) for b in blobs]

        # give the spill monitor time to react to the pressure
        nm = worker_mod._global_cluster.nm
        deadline = time.time() + 15
        while time.time() < deadline and not nm._spilled:
            time.sleep(0.2)
        assert nm._spilled, "nothing spilled under pressure"

        for i, ref in enumerate(refs):
            out = ray_tpu.get(ref)
            np.testing.assert_array_equal(out, blobs[i])
    finally:
        ray_tpu.shutdown()


def test_spilled_objects_served_to_tasks():
    ray_tpu.init(num_cpus=2, object_store_memory=8 * 1024 * 1024)
    try:
        big = [ray_tpu.put(np.full(2 * 1024 * 1024 // 8, i, np.int64))
               for i in range(6)]
        nm = worker_mod._global_cluster.nm
        deadline = time.time() + 15
        while time.time() < deadline and not nm._spilled:
            time.sleep(0.2)

        @ray_tpu.remote
        def total(arr):
            return int(arr[0])

        # Workers fetch (possibly spilled) args through the store/NM path.
        outs = ray_tpu.get([total.remote(r) for r in big], timeout=60)
        assert outs == [0, 1, 2, 3, 4, 5]
    finally:
        ray_tpu.shutdown()
