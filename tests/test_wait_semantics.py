"""Pin ray.wait() semantics (reference: core_worker Wait + the public
contract in python/ray/_private/worker.py wait docstring):

- ready contains at most num_returns refs, in the order of the input;
- a FAILED object counts as ready (so a follow-up get raises promptly
  instead of hanging);
- timeout=0 is a non-blocking poll;
- fetch_local=False only answers availability, it does not pull.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_wait_preserves_input_order(ray_4cpu):
    @ray_tpu.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(6)]
    ray_tpu.get(list(refs))  # all complete
    ready, not_ready = ray_tpu.wait(refs, num_returns=3, timeout=5)
    assert ready == refs[:3]
    assert not_ready == refs[3:]


def test_failed_object_counts_as_ready(ray_4cpu):
    @ray_tpu.remote
    def boom():
        raise ValueError("expected")

    @ray_tpu.remote
    def hang():
        time.sleep(30)

    bad, slow = boom.remote(), hang.remote()
    ready, not_ready = ray_tpu.wait([bad, slow], num_returns=1, timeout=10)
    assert ready == [bad]
    assert not_ready == [slow]
    with pytest.raises(ValueError, match="expected"):
        ray_tpu.get(bad)


def test_wait_timeout_zero_is_poll(ray_4cpu):
    @ray_tpu.remote
    def hang():
        time.sleep(30)

    ref = hang.remote()
    t0 = time.time()
    ready, not_ready = ray_tpu.wait([ref], timeout=0)
    assert time.time() - t0 < 2.0
    assert ready == [] and not_ready == [ref]

    done = ray_tpu.put(1)
    ready, not_ready = ray_tpu.wait([done, ref], timeout=0)
    assert ready == [done] and not_ready == [ref]


def test_wait_fetch_local_false_does_not_pull(ray_4cpu):
    """fetch_local=False answers availability without copying the object
    into the caller's store; fetch_local=True pulls it."""
    # Single-node: contains() is immediate; use a cross-node cluster.
    ray_tpu.shutdown()
    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    other = cluster.add_node(num_cpus=2)
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    try:
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        @ray_tpu.remote
        def produce():
            return np.ones(1 << 16, np.uint8)

        ref = produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=other.node_id, soft=False)).remote()
        w = worker_mod.require_worker()

        ready, _ = ray_tpu.wait([ref], timeout=30, fetch_local=False)
        assert ready == [ref]
        assert not w.store.contains(ref.binary())  # stayed remote

        ready, _ = ray_tpu.wait([ref], timeout=30, fetch_local=True)
        assert ready == [ref]
        deadline = time.time() + 10
        while not w.store.contains(ref.binary()) and time.time() < deadline:
            time.sleep(0.05)
        assert w.store.contains(ref.binary())  # pulled locally
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_wait_duplicate_refs_rejected(ray_4cpu):
    ref = ray_tpu.put(1)
    with pytest.raises(ValueError):
        ray_tpu.wait([ref, ref])
