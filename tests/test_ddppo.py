"""DD-PPO: decentralized allreduce training on the collective layer
(reference: rllib/algorithms/ddppo/ddppo.py:90,173,220 — learning on the
rollout workers, gradient sync via distributed allreduce, no central
learner)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import DDPPOConfig
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, LOGPS, OBS, RETURNS, SampleBatch,
)


def _cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


@pytest.fixture
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _synthetic_batch(seed, n=32, obs_dim=4, num_actions=2):
    rng = np.random.default_rng(seed)
    return SampleBatch({
        OBS: rng.normal(size=(n, obs_dim)).astype(np.float32),
        ACTIONS: rng.integers(0, num_actions, n).astype(np.int32),
        LOGPS: rng.normal(scale=0.1, size=n).astype(np.float32),
        ADVANTAGES: rng.normal(size=n).astype(np.float32),
        RETURNS: rng.normal(size=n).astype(np.float32),
    })


def _flat(params):
    from jax.flatten_util import ravel_pytree

    return np.asarray(ravel_pytree(params)[0])


def test_ddppo_gradient_equivalence_with_central(ray_cluster):
    """One decentralized update (2 ranks, different data, allreduce-AVG)
    must equal the centralized update that applies the equally-weighted
    mean of the two per-rank gradients — the DDP invariant."""
    from ray_tpu.rllib.ddppo import _DDPPOWorker
    from ray_tpu.rllib.policy import PolicySpec
    from ray_tpu.rllib.ppo import PPOLearner

    cfg = DDPPOConfig(num_rollout_workers=2, rollout_fragment_length=16,
                      obs_dim=4, num_actions=2, seed=5)
    cfg.environment(_cartpole)
    spec = PolicySpec(4, 2)
    b0, b1 = _synthetic_batch(1), _synthetic_batch(2)

    worker_cls = ray_tpu.remote(_DDPPOWorker)
    gang = [worker_cls.remote(_cartpole, spec, cfg, 2, r, "eqtest")
            for r in range(2)]
    ray_tpu.get([w.join.remote() for w in gang])
    ray_tpu.get([w.train_iteration.remote(1, 10_000, b)
                 for w, b in zip(gang, (b0, b1))])
    w0, w1 = ray_tpu.get([w.get_weights.remote() for w in gang])
    # Ranks identical after the update (replication invariant).
    np.testing.assert_allclose(_flat(w0), _flat(w1), atol=1e-6)

    # Centralized reference: same init, mean of per-batch grads, applied
    # once (LearnerGroup._average with equal counts).
    import jax

    central = PPOLearner(spec, cfg)
    g0, _ = central.compute_grads(dict(b0))
    g1, _ = central.compute_grads(dict(b1))
    avg = jax.tree.map(lambda a, b: (a + b) / 2, g0, g1)
    central.apply_grads(avg)
    np.testing.assert_allclose(_flat(w0), _flat(central.get_weights()),
                               atol=1e-5)
    for w in gang:
        ray_tpu.kill(w)


def test_ddppo_end_to_end_stays_in_sync(ray_cluster):
    """Full DDPPO Algorithm on CartPole: iterations run with NO central
    learner, ranks remain bit-identical across sampled (different) data,
    and metrics flow."""
    algo = (DDPPOConfig(num_sgd_epochs=2, sgd_minibatch_size=64)
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=64)
            .build())
    try:
        for _ in range(2):
            metrics = algo.train()
        assert metrics["timesteps_this_iter"] == 2 * 64
        assert "total_loss" in metrics
        w = [ray_tpu.get(a.get_weights.remote()) for a in algo.workers]
        np.testing.assert_allclose(_flat(w[0]), _flat(w[1]), atol=1e-6)
        # Checkpoint round-trips through the gang facade.
        state = algo.learner.get_state()
        algo.learner.set_state(state)
    finally:
        algo.stop()
