"""Worker pool: prestarted CPU workers serving actors, and chip-bound
(TPU) worker reuse between same-shape tasks.

Reference behaviors: worker_pool.h:344 (prestart), worker_pool.h:340
(PopWorker serves actor-creation tasks from the pool), worker_pool.h:156
(pools keyed by runtime-env hash — here chip shape + spawn env).
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def tpu_cluster():
    ctx = ray_tpu.init(num_cpus=2, num_tpus=2,
                       object_store_memory=64 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _head_nm():
    from ray_tpu._private import worker as worker_mod

    return worker_mod._global_cluster.nm


def test_tpu_worker_reused_same_shape(tpu_cluster):
    """A second TPU task of the same chip shape reuses the parked worker
    (same pid, same TPU_VISIBLE_CHIPS) instead of paying a fresh spawn +
    XLA client init."""
    @ray_tpu.remote(num_tpus=1)
    def chip_pid():
        import os
        return os.getpid(), os.environ.get("TPU_VISIBLE_CHIPS")

    pid1, chips1 = ray_tpu.get(chip_pid.remote())
    pid2, chips2 = ray_tpu.get(chip_pid.remote())
    assert pid1 == pid2
    assert chips1 == chips2 and chips1 is not None
    nm = _head_nm()
    assert any(pool for pool in nm._tpu_idle.values())


def test_tpu_pool_reclaim_for_bigger_shape(tpu_cluster):
    """When free chips can't cover a larger request, parked chip-bound
    workers are evicted and their chips reassigned — a parked pool must
    never wedge differently-shaped TPU work."""
    @ray_tpu.remote(num_tpus=1)
    def one():
        import os
        return os.getpid()

    pid_small = ray_tpu.get(one.remote())

    @ray_tpu.remote(num_tpus=2)
    def two():
        import os
        return (os.getpid(), os.environ.get("TPU_VISIBLE_CHIPS"))

    pid_big, chips = ray_tpu.get(two.remote(), timeout=60)
    assert pid_big != pid_small
    assert sorted(chips.split(",")) == ["0", "1"]


def test_tpu_worker_not_shared_across_env_vars(tpu_cluster):
    """Tasks with different runtime_env env_vars must not share a parked
    worker (env is burned in at spawn)."""
    @ray_tpu.remote(num_tpus=1)
    def probe():
        import os
        return os.getpid(), os.environ.get("MARK")

    @ray_tpu.remote(num_tpus=1, runtime_env={"env_vars": {"MARK": "x"}})
    def probe_marked():
        import os
        return os.getpid(), os.environ.get("MARK")

    pid_a, mark_a = ray_tpu.get(probe.remote())
    pid_b, mark_b = ray_tpu.get(probe_marked.remote(), timeout=60)
    assert mark_a is None and mark_b == "x"
    assert pid_a != pid_b
    # Same-env resubmission reuses its own worker.
    pid_b2, _ = ray_tpu.get(probe_marked.remote(), timeout=60)
    assert pid_b2 == pid_b


def test_actor_served_from_prestarted_pool(tpu_cluster):
    """Plain actors take over a prestarted pool worker (no cold spawn)
    and the pool refills in the background."""
    nm = _head_nm()
    deadline = time.time() + 30
    while time.time() < deadline:   # wait for the prestarted pool
        with nm._lock:
            pool_pids = {w.proc.pid for w in nm._workers.values()
                         if not w.dedicated}
        if len(pool_pids) >= 2 and nm._idle:
            break
        time.sleep(0.1)
    assert pool_pids

    @ray_tpu.remote
    class A:
        def pid(self):
            import os
            return os.getpid()

    a = A.remote()
    actor_pid = ray_tpu.get(a.pid.remote(), timeout=30)
    assert actor_pid in pool_pids   # took over a prestarted worker
    # Pool refills to max_pool in the background.
    deadline = time.time() + 30
    while time.time() < deadline:
        with nm._lock:
            n = len([w for w in nm._workers.values()
                     if not w.dedicated and w.state != "dead"])
        if n >= nm._max_pool:
            break
        time.sleep(0.1)
    assert n >= nm._max_pool


def test_actor_create_rate_improved(tpu_cluster):
    """Pool-served actor creation sustains a healthy rate on a cold-spawn
    budget that fresh spawns could never hit (SCALE_r04: 5.75/s)."""
    @ray_tpu.remote
    class P:
        def ping(self):
            return 1

    # Sequential create+ping pairs; pool refill keeps feeding workers.
    t0 = time.time()
    n = 6
    for _ in range(n):
        p = P.remote()
        assert ray_tpu.get(p.ping.remote(), timeout=30) == 1
    rate = n / (time.time() - t0)
    # Very conservative floor: a cold python+jax spawn per actor runs
    # ~0.2/s sequentially on this box.
    assert rate > 1.0, rate
