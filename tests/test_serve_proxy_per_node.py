"""Proxy-per-node ingress (reference: serve/_private/http_state.py:28
HTTPState starts an HTTPProxyActor on every node; http_proxy.py:415):
route tables PUSH to all proxies, and ingress survives a proxy node's
death."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.cluster_utils import Cluster

PORT = 18551


@pytest.fixture
def two_node_serve():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 3})
    worker_nm = cluster.add_node(num_cpus=2)
    cluster.connect(object_store_memory=96 * 1024 * 1024)
    cluster.wait_for_nodes()
    serve.start(http_port=PORT)
    yield cluster, worker_nm
    serve.shutdown()
    ray_tpu.shutdown()
    cluster.shutdown()


def _get(port, path, timeout=30):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _proxy_ports(deadline_s=30, expect=2):
    from ray_tpu.serve.controller import CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        addrs = ray_tpu.get(ctrl.proxy_addresses.remote())
        if len(addrs) >= expect:
            return addrs
        time.sleep(0.3)
    return ray_tpu.get(ctrl.proxy_addresses.remote())


def test_proxy_per_node_and_failover(two_node_serve):
    cluster, worker_nm = two_node_serve

    @serve.deployment(num_replicas=2)
    def hello(payload):
        return {"hello": payload.get("query", {}).get("name", "world")}

    serve.run(hello.bind(), route_prefix="/hello", http_port=PORT)

    # One proxy per node, all serving the SAME route table.
    addrs = _proxy_ports(expect=2)
    assert len(addrs) == 2, addrs
    ports = sorted(addrs.values())
    for p in ports:
        out = _get(p, "/hello?name=tpu")
        assert out == {"hello": "tpu"}

    # Kill the worker node: its proxy (and any replica there) dies.
    worker_nid = worker_nm.node_id
    head_ports = [port for nid, port in addrs.items()
                  if nid != worker_nid]
    assert head_ports, addrs
    cluster.remove_node(worker_nm, allow_graceful=False)

    # Ingress on the surviving node keeps working (replicas reconcile
    # back onto live nodes; handle resubmits through replica death).
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        try:
            if _get(head_ports[0], "/hello?name=x",
                    timeout=10) == {"hello": "x"}:
                ok = True
                break
        except Exception:
            time.sleep(0.5)
    assert ok
    # The dead node's proxy drops from the table.
    deadline = time.time() + 30
    while time.time() < deadline:
        addrs2 = _proxy_ports(expect=1)
        if worker_nid not in addrs2:
            break
        time.sleep(0.5)
    assert worker_nid not in addrs2, addrs2


def test_route_table_pushes_to_proxies(two_node_serve):
    """A new deployment is routable on EVERY node's proxy within one
    push (no TTL wait): deploy, then immediately hit both proxies."""
    @serve.deployment
    def ping(payload):
        return {"pong": True}

    serve.run(ping.bind(), route_prefix="/ping", http_port=PORT)
    addrs = _proxy_ports(expect=2)
    for p in addrs.values():
        assert _get(p, "/ping") == {"pong": True}
