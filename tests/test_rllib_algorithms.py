"""Tests for the expanded RLlib family: V-trace math, IMPALA, A2C,
LearnerGroup DP, Algorithm checkpointing (reference analogs:
rllib/algorithms/impala, a2c, core/learner/learner_group.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    A2CConfig, IMPALAConfig, PPOConfig, PPOLearner, LearnerGroup,
)
from ray_tpu.rllib.policy import PolicySpec
from ray_tpu.rllib.sample_batch import (
    ACTIONS, ADVANTAGES, LOGPS, OBS, RETURNS, SampleBatch,
)


def _cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_vtrace_on_policy_reduces_to_nstep():
    """With target == behavior policy and rho/c thresholds >= 1, V-trace
    targets equal the on-policy n-step bootstrapped returns."""
    import jax.numpy as jnp

    from ray_tpu.rllib.vtrace import vtrace

    T, gamma = 5, 0.9
    rng = np.random.default_rng(0)
    rewards = rng.normal(size=T).astype(np.float32)
    values = rng.normal(size=T).astype(np.float32)
    bootstrap = 0.7
    next_values = np.append(values[1:], np.float32(bootstrap))
    logp = rng.normal(size=T).astype(np.float32)
    discounts = np.full(T, gamma, np.float32)

    out = vtrace(jnp.array(logp), jnp.array(logp), jnp.array(rewards),
                 jnp.array(values), jnp.array(next_values),
                 jnp.array(discounts))
    # on-policy: vs_t = r_t + gamma * vs_{t+1}, vs_T-tail bootstraps
    expected = np.zeros(T, np.float32)
    acc = bootstrap
    for t in range(T - 1, -1, -1):
        acc = rewards[t] + gamma * acc
        expected[t] = acc
    np.testing.assert_allclose(np.asarray(out.vs), expected, rtol=1e-5)


def test_vtrace_clipping_bounds_correction():
    """Huge off-policy ratios must be clipped: targets stay finite and
    between the behavior-value estimate and the on-policy extreme."""
    import jax.numpy as jnp

    from ray_tpu.rllib.vtrace import vtrace

    T = 4
    behavior = np.zeros(T, np.float32)
    target = np.full(T, 5.0, np.float32)  # ratio e^5 ~ 148, clipped to 1
    rewards = np.ones(T, np.float32)
    values = np.zeros(T, np.float32)
    next_values = np.append(values[1:], np.float32(0.0))
    discounts = np.full(T, 0.9, np.float32)
    out = vtrace(jnp.array(behavior), jnp.array(target), jnp.array(rewards),
                 jnp.array(values), jnp.array(next_values),
                 jnp.array(discounts))
    clipped = vtrace(jnp.array(behavior), jnp.array(behavior),
                     jnp.array(rewards), jnp.array(values),
                     jnp.array(next_values), jnp.array(discounts))
    # with rho clipped at 1 the two must coincide exactly
    np.testing.assert_allclose(np.asarray(out.vs),
                               np.asarray(clipped.vs), rtol=1e-5)


def test_impala_cartpole_learns(ray_cluster):
    algo = (IMPALAConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=2e-3, entropy_coeff=0.02)
            .build())
    returns = []
    for _ in range(20):
        m = algo.train()
        assert m["fragments_this_iter"] >= 1
        if m["episode_return_mean"] is not None:
            returns.append(m["episode_return_mean"])
    algo.stop()
    assert m["timesteps_total"] > 2000
    assert max(returns[-4:]) > returns[0] + 15, returns


def test_a2c_cartpole_learns(ray_cluster):
    algo = (A2CConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(lr=2e-3)
            .build())
    returns = []
    for _ in range(15):
        m = algo.train()
        if m["episode_return_mean"] is not None:
            returns.append(m["episode_return_mean"])
    algo.stop()
    assert max(returns[-4:]) > returns[0] + 15, returns


def _random_ppo_batch(n=256):
    rng = np.random.default_rng(0)
    return SampleBatch({
        OBS: rng.normal(size=(n, 4)).astype(np.float32),
        ACTIONS: rng.integers(0, 2, n).astype(np.int32),
        LOGPS: np.full(n, -0.69, np.float32),
        ADVANTAGES: rng.normal(size=n).astype(np.float32),
        RETURNS: rng.normal(size=n).astype(np.float32),
    })


def test_learner_group_matches_single_learner(ray_cluster):
    """DP invariants: (a) the learner replicas stay bit-identical after
    updates (the DDP replication invariant, exact); (b) the group tracks a
    single learner on the same batch closely — not exactly, because PPO
    normalizes advantages within each learner's shard, so the sharded
    loss surface differs from the full-batch one by O(shard-stat noise)."""
    import ray_tpu as rt
    spec = PolicySpec(obs_dim=4, num_actions=2)
    cfg = PPOConfig(seed=3)
    batch = _random_ppo_batch(128)
    rng1, rng2 = (np.random.default_rng(1), np.random.default_rng(1))

    single = PPOLearner(spec, cfg)
    group = LearnerGroup(lambda: PPOLearner(spec, cfg), num_learners=2)
    try:
        m_single = single.update_from_batch(batch, num_epochs=2,
                                            minibatch_size=128, rng=rng1)
        m_group = group.update_from_batch(batch, num_epochs=2,
                                          minibatch_size=128, rng=rng2)
        assert m_single.keys() == m_group.keys()
        import jax

        # (a) replicas identical
        w0, w1 = rt.get([s.get_weights.remote() for s in group._shards])
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), w0, w1)
        # (b) group ~= single
        w_s, w_g = single.get_weights(), group.get_weights()
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-3), w_s, w_g)
    finally:
        group.stop()


def test_ppo_with_learner_group(ray_cluster):
    algo = (PPOConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=128)
            .training(num_sgd_epochs=2, sgd_minibatch_size=128,
                      num_learners=2)
            .build())
    m = algo.train()
    assert m["timesteps_this_iter"] == 256
    assert "total_loss" in m
    algo.stop()


def test_algorithm_checkpoint_roundtrip(ray_cluster, tmp_path):
    algo = (A2CConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=1, rollout_fragment_length=64)
            .build())
    algo.train()
    algo.train()
    path = algo.save_checkpoint(str(tmp_path / "ckpt"))
    assert path.endswith("algorithm_state.pkl")

    algo2 = (A2CConfig()
             .environment(_cartpole)
             .rollouts(num_rollout_workers=1, rollout_fragment_length=64)
             .build())
    algo2.restore_checkpoint(str(tmp_path / "ckpt"))
    assert algo2.iteration == 2
    assert algo2.timesteps_total == algo.timesteps_total
    import jax

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                np.asarray(b)),
        algo.get_weights(), algo2.get_weights())
    algo.stop()
    algo2.stop()


def test_sac_learner_fits_critic():
    """The jitted SAC update (twin critics + reparameterized actor +
    auto-alpha + polyak targets) fits a fixed batch: critic loss falls,
    alpha stays positive, entropy is finite."""
    from ray_tpu.rllib import (
        ContinuousPolicySpec, ContinuousReplayBuffer, SACConfig, SACLearner,
    )

    rng = np.random.default_rng(0)
    spec = ContinuousPolicySpec(obs_dim=3, action_dim=1,
                                action_low=-2.0, action_high=2.0,
                                hidden=(32, 32))
    learner = SACLearner(spec, SACConfig(seed=0, lr=3e-3))
    buf = ContinuousReplayBuffer(10_000, 3, 1)
    obs = rng.normal(size=(1000, 3)).astype(np.float32)
    act = rng.uniform(-2, 2, size=(1000, 1)).astype(np.float32)
    rew = (-(obs[:, 0] ** 2) - 0.1 * act[:, 0] ** 2).astype(np.float32)
    buf.add_batch(obs, act, rew, obs, np.zeros(1000, np.float32))

    m1 = learner.update_from_buffer(buf, 5, 128, rng)
    for _ in range(20):
        m2 = learner.update_from_buffer(buf, 5, 128, rng)
    assert m2["critic_loss"] < m1["critic_loss"]
    assert m2["alpha"] > 0
    assert np.isfinite(m2["entropy"])
    # Checkpoint round-trip includes targets + alpha state.
    state = learner.get_state()
    learner2 = SACLearner(spec, SACConfig(seed=1))
    learner2.set_state(state)
    import jax
    jax.tree.map(np.testing.assert_allclose, learner.params,
                 learner2.params)


def test_sac_pendulum_end_to_end(ray_cluster):
    """SAC plumbing on a real continuous env: rollout actors sample
    tanh-Gaussian actions within bounds, the buffer fills, and updates
    run (full convergence needs ~10k+ steps — out of CI budget)."""
    import gymnasium as gym

    from ray_tpu.rllib import SACConfig

    algo = (SACConfig()
            .environment(lambda: gym.make("Pendulum-v1"))
            .rollouts(num_rollout_workers=1, rollout_fragment_length=200)
            .training(lr=3e-3, learning_starts=200, num_sgd_iters=8,
                      train_batch_size=64, seed=0)
            .build())
    try:
        for _ in range(4):
            m = algo.train()
        assert m["timesteps_total"] == 800
        assert m["buffer_size"] == 800
        assert np.isfinite(m["critic_loss"])
        assert m["alpha"] > 0
        # Actions respected the Box bounds.
        a = algo.buffer.actions[:algo.buffer.size]
        assert a.min() >= -2.0 - 1e-5 and a.max() <= 2.0 + 1e-5
    finally:
        algo.stop()


def test_offline_json_roundtrip_and_bc(tmp_path, ray_cluster):
    """Offline RL: record experiences with JsonWriter, read them back,
    and behavior-clone a policy that matches the (deterministic) expert
    on its states (reference: rllib/offline + algorithms/bc)."""
    from ray_tpu.rllib import BCConfig, JsonReader, JsonWriter
    from ray_tpu.rllib.sample_batch import ACTIONS, OBS

    rng = np.random.default_rng(0)
    path = str(tmp_path / "exp")
    writer = JsonWriter(path)
    # Expert: action = 1 iff obs[0] > 0 (learnable deterministic rule).
    for _ in range(6):
        obs = rng.normal(size=(128, 4)).astype(np.float32)
        acts = (obs[:, 0] > 0).astype(np.int32)
        writer.write(SampleBatch({OBS: obs, ACTIONS: acts}))
    writer.close()

    data = JsonReader(path).read_all()
    assert data.count == 6 * 128

    import gymnasium as gym
    algo = (BCConfig(input_path=path)
            .environment(lambda: gym.make("CartPole-v1"))
            .training(lr=3e-3, sgd_iters_per_step=40,
                      train_batch_size=256, seed=0)
            .build())
    try:
        m1 = algo.train()
        for _ in range(4):
            m2 = algo.train()
        assert m2["bc_loss"] < m1["bc_loss"]
        # Cloned policy reproduces the expert rule.
        from ray_tpu.rllib.policy import MLPPolicy
        test_obs = rng.normal(size=(256, 4)).astype(np.float32)
        logits, _ = MLPPolicy.forward(algo.learner.params, test_obs)
        pred = np.argmax(np.asarray(logits), axis=1)
        agree = (pred == (test_obs[:, 0] > 0)).mean()
        assert agree > 0.9, agree
    finally:
        algo.stop()


class _TagTeamEnv:
    """Toy 2-agent env: each agent sees a +/-1 cue and must answer with
    the matching action; one agent's cue is INVERTED so the two agents
    need different policies — a policy-map test, not a broadcast test."""

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._t = 0

    def reset(self, seed=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        return self._draw(), {}

    def _draw(self):
        self._cue = int(self._rng.integers(0, 2))
        obs = np.asarray([2.0 * self._cue - 1.0], np.float32)
        return {"a0": obs, "a1": -obs}

    def step(self, actions):
        rew = {"a0": float(actions["a0"] == self._cue),
               "a1": float(actions["a1"] == self._cue)}
        self._t += 1
        done = self._t >= 16
        obs = self._draw()
        term = {"a0": done, "a1": done, "__all__": done}
        trunc = {"__all__": False}
        return obs, rew, term, trunc, {}


def test_multi_agent_policy_map_learns(ray_cluster):
    """Two agents with OPPOSITE observation conventions learn under two
    mapped policies (reference: multi-agent policy maps +
    policy_mapping_fn)."""
    from ray_tpu.rllib import MultiAgentPPOConfig
    from ray_tpu.rllib.policy import PolicySpec

    spec = PolicySpec(obs_dim=1, num_actions=2, hidden=(16,))
    algo = (MultiAgentPPOConfig()
            .environment(_TagTeamEnv)
            .rollouts(num_rollout_workers=1, rollout_fragment_length=256)
            .training(lr=3e-3, num_sgd_epochs=4, sgd_minibatch_size=64,
                      seed=0)
            .multi_agent(policies={"even": spec, "odd": spec},
                         policy_mapping_fn=lambda agent:
                         "even" if agent == "a0" else "odd")
            .build())
    try:
        returns = []
        for _ in range(14):
            m = algo.train()
            if m["episode_return_mean"] is not None:
                returns.append(m["episode_return_mean"])
        # 16 steps x 2 agents x ~1.0 reward when solved = ~32; random ~16.
        assert returns[-1] > returns[0] + 4, returns
        assert any(k.startswith("even/") for k in m)
        assert any(k.startswith("odd/") for k in m)
    finally:
        algo.stop()


def test_connector_pipeline_units():
    """Connector transforms (reference: rllib/connectors/): flatten,
    clip, running mean-std normalization with syncable state, action
    clipping, and ordered composition."""
    from ray_tpu.rllib import (
        ClipAction, ClipObs, ConnectorPipeline, FlattenObs, MeanStdFilter,
    )

    pipe = ConnectorPipeline([FlattenObs(), ClipObs(-2, 2),
                              MeanStdFilter()])
    rng = np.random.default_rng(0)
    for _ in range(200):
        out = pipe.transform_obs(rng.normal(3.0, 2.0, size=(2, 2)))
    assert out.shape == (4,)
    # After 200 samples of N(3,2) clipped at 2, normalized output is
    # near zero-mean unit-ish scale.
    assert abs(float(out.mean())) < 3.0

    # State sync round-trip: a fresh pipeline adopting the state
    # produces the same normalization.
    pipe2 = ConnectorPipeline([FlattenObs(), ClipObs(-2, 2),
                               MeanStdFilter()])
    pipe2.set_state(pipe.get_state())
    x = np.full((2, 2), 1.5)
    a = pipe.transform_obs(x.copy())
    b = pipe2.transform_obs(x.copy())
    np.testing.assert_allclose(a, b, rtol=1e-5)

    ca = ClipAction([-1.0, -0.5], [1.0, 0.5])
    np.testing.assert_allclose(ca.transform_action([3.0, -3.0]),
                               [1.0, -0.5])


def test_connectors_in_rollout(ray_cluster):
    """A rollout worker with a connector pipeline trains PPO end to end
    (obs normalized before the policy on every step)."""
    from ray_tpu.rllib import ConnectorPipeline, MeanStdFilter, PPOConfig
    from ray_tpu.rllib.policy import PolicySpec
    from ray_tpu.rllib.rollout_worker import RolloutWorker

    import gymnasium as gym

    spec = PolicySpec(obs_dim=4, num_actions=2)
    w = RolloutWorker(lambda: gym.make("CartPole-v1"), spec,
                      rollout_fragment_length=64, seed=0,
                      connectors=ConnectorPipeline([MeanStdFilter()]))
    from ray_tpu.rllib import PPOLearner
    learner = PPOLearner(spec, PPOConfig())
    batch = w.sample(learner.get_weights())
    assert batch.count == 64
    # Stored observations are the TRANSFORMED ones the policy saw.
    from ray_tpu.rllib.sample_batch import OBS
    assert abs(float(np.asarray(batch[OBS]).mean())) < 5.0
