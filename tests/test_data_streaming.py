"""Streaming data executor + Train ingest (reference:
_internal/execution/streaming_executor.py:35; air get_dataset_shard)."""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rdata


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_streaming_matches_bulk(ray_4cpu):
    ds = rdata.range(100, parallelism=8).map(lambda x: x * 3)
    streamed = [r for rows in ds.iter_block_results() for r in rows]
    bulk = ds.take_all()
    assert sorted(streamed) == sorted(bulk) == [3 * i for i in range(100)]


def test_streaming_bounded_in_flight(ray_4cpu, tmp_path):
    """With prefetch_blocks=1, consuming the first block must not have
    executed every block (execution is demand-driven, not bulk)."""
    marker_dir = str(tmp_path)

    def touch(x):
        open(os.path.join(marker_dir, f"b{os.getpid()}_{x}"), "w").close()
        return x

    ds = rdata.range(8, parallelism=8).map(touch)
    it = ds.iter_block_results(prefetch_blocks=1)
    next(it)
    time.sleep(0.3)  # let any in-flight prefetch land
    executed_early = len(os.listdir(marker_dir))
    assert executed_early <= 4, (
        f"{executed_early} rows executed after first block with "
        f"prefetch_blocks=1 — looks like bulk execution")
    rest = sum(len(rows) for rows in it)
    assert rest == 7


def test_iter_batches_streams(ray_4cpu):
    ds = rdata.range(64, parallelism=8).map(lambda x: {"v": x})
    seen = []
    for batch in ds.iter_batches(batch_size=16):
        assert set(batch) == {"v"}
        seen.extend(batch["v"].tolist())
    assert sorted(seen) == list(range(64))


def test_streaming_split_is_lazy_and_disjoint(ray_4cpu):
    ds = rdata.range(40, parallelism=8).map(lambda x: x + 1000)
    shards = ds.streaming_split(4)
    got = [sorted(s.take_all()) for s in shards]
    all_rows = sorted(r for g in got for r in g)
    assert all_rows == [i + 1000 for i in range(40)]
    # disjoint
    assert sum(len(g) for g in got) == 40


def test_train_ingest_with_dataset_shard(ray_4cpu, tmp_path):
    """get_dataset_shard inside the train loop streams this rank's blocks;
    the union of what the gang consumed covers the dataset disjointly."""
    from ray_tpu.train import DataParallelTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu import train

        shard = train.get_dataset_shard("train")
        total, n = 0, 0
        for batch in shard.iter_batches(batch_size=8):
            total += int(batch["x"].sum())
            n += len(batch["x"])
        train.report({"n": n, "total": total,
                      "rank": train.get_world_rank()})

    ds = rdata.range(60, parallelism=6).map(lambda x: {"x": x})
    trainer = DataParallelTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ingest", storage_path=str(tmp_path)),
        datasets={"train": ds},
        backend="store",
    )
    result = trainer.fit()
    assert result.ok, result.error
    # rank 0's report only reaches history; verify coverage via totals:
    # every row consumed exactly once across the gang.
    # (rank0 + rank1 ns sum to 60 and totals to sum(range(60)))
    n0 = result.metrics_history[-1]["n"]
    t0 = result.metrics_history[-1]["total"]
    assert 0 < n0 < 60  # rank 0 got a strict subset (split happened)


def test_dataset_pipeline_windows(ray_4cpu):
    ds = rdata.range(40, parallelism=8)
    pipe = ds.window(blocks_per_window=2).map(lambda x: x * 2)
    rows = [r for r in pipe.iter_rows()]
    assert sorted(rows) == [2 * i for i in range(40)]
    assert pipe.length == 4


def test_dataset_pipeline_repeat_epochs(ray_4cpu):
    ds = rdata.range(10, parallelism=2)
    pipe = ds.repeat(3)
    rows = list(pipe.iter_rows())
    assert len(rows) == 30
    assert sorted(set(rows)) == list(range(10))


def test_dataset_pipeline_batches_across_windows(ray_4cpu):
    pipe = rdata.range(24, parallelism=4).window(blocks_per_window=1)
    batches = list(pipe.iter_batches(batch_size=6))
    total = sum(len(b["item"]) for b in batches)
    assert total == 24
