"""Decentralized actor creation (NM-local actor leases).

The actor analog of local-first task scheduling: the driver asks its OWN
node manager to place eligible actors from the node's ledger
(request_create_actor); the GCS learns of the placement asynchronously
(actor_placed, same-conn-FIFO-ordered before any actor_state). Covered
here, per the SCALE_r06 issue:

- NM-local placement happy path (grant counters, GCS directory entry,
  resource reconciliation through the local_held aggregate);
- GCS spillback when the node is full (decline -> classic scheduled
  creation, placement once capacity frees);
- NM death with an in-flight locally-created actor (re-placed through
  the GCS on a surviving node; driver re-creates when the placement
  report itself was lost);
- a concurrent create/kill race (ray.kill overtaking the actor_placed
  report: the kill tombstone completes on arrival).
"""

import gc
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.gcs import DEAD, GcsServer
from ray_tpu._private.node_manager import NodeManager


def _wait_until(pred, timeout=30, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if pred():
                return
        except Exception:
            pass
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _own_nm():
    # Match the LIVE cluster: earlier tests' (shut down) NodeManagers
    # linger in gc until collected.
    from ray_tpu._private import worker as wm

    w = wm.global_worker()
    return [o for o in gc.get_objects() if isinstance(o, NodeManager)
            and not o._shutdown and o.gcs_address == w.gcs_address][0]


def _gcs():
    from ray_tpu._private import worker as wm

    w = wm.global_worker()
    return [o for o in gc.get_objects() if isinstance(o, GcsServer)
            and o.address == w.gcs_address][0]


@ray_tpu.remote(num_cpus=0)
class Pinger:
    def __init__(self, x=0):
        self.x = x

    def ping(self):
        return self.x


def test_local_creation_happy_path():
    """Eligible actors place through the local NM: no GCS scheduling,
    grant counter bumps, the GCS directory entry is the NM's async
    placement report, and kill returns the local_held resources."""
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        nm = _own_nm()
        gcs = _gcs()
        base_grants = nm.local_actor_grants_total
        actors = [Pinger.remote(i) for i in range(8)]
        assert ray_tpu.get([a.ping.remote() for a in actors],
                           timeout=60) == list(range(8))
        assert nm.local_actor_grants_total - base_grants == 8
        # The GCS learned of every placement via actor_placed, flagged
        # as locally-placed (its resources ride the local_held
        # aggregate, never the central ledger).
        with gcs._actor_lock:
            local_entries = [e for e in gcs._actors.values()
                            if e.local_placement and e.state == "ALIVE"]
        assert len(local_entries) >= 8
        for a in actors:
            ray_tpu.kill(a)
        # Death drains both the NM's actor registry and the aggregate.
        _wait_until(lambda: not nm._local_actor_ids,
                    msg="local actor ids drained")
        _wait_until(lambda: nm._local_held.is_zero(),
                    msg="local_held drained after kills")
    finally:
        ray_tpu.shutdown()


def test_ineligible_actor_takes_classic_path():
    """Named actors keep the GCS-scheduled path (name uniqueness is
    central) — and still work."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        nm = _own_nm()
        base = nm.local_actor_grants_total
        a = Pinger.options(name="pinger-classic").remote(7)
        assert ray_tpu.get(a.ping.remote(), timeout=30) == 7
        assert nm.local_actor_grants_total == base
        got = ray_tpu.get_actor("pinger-classic")
        assert ray_tpu.get(got.ping.remote(), timeout=30) == 7
    finally:
        ray_tpu.shutdown()


def test_spillback_when_node_full():
    """A local decline (no capacity) falls back to the classic
    GCS-scheduled creation; the actor places once capacity frees."""
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        nm = _own_nm()

        @ray_tpu.remote(num_cpus=2)
        class Big:
            def ping(self):
                return "big"

        a1 = Big.remote()
        a2 = Big.remote()
        assert ray_tpu.get([a1.ping.remote(), a2.ping.remote()],
                           timeout=60) == ["big", "big"]
        base_spill = nm.local_actor_spillbacks_total
        a3 = Big.remote()
        _wait_until(lambda: nm.local_actor_spillbacks_total > base_spill,
                    msg="local decline recorded")
        # No capacity anywhere: a3 must be pending, not failed.
        ref = a3.ping.remote()
        ready, not_ready = ray_tpu.wait([ref], timeout=1.0)
        assert not ready
        # Free capacity; the GCS-scheduled path places a3.
        ray_tpu.kill(a1)
        assert ray_tpu.get(ref, timeout=60) == "big"
    finally:
        ray_tpu.shutdown()


def test_nm_death_replaces_actor_via_gcs(tmp_path):
    """The node hosting a locally-created actor dies: the GCS (which
    learned of the actor via actor_placed) restarts it on a surviving
    node through the central scheduler."""
    gcs = GcsServer()
    nm_head = NodeManager(
        gcs_address=gcs.address,
        session_dir=str(tmp_path / "s1"),
        num_cpus=2, num_tpus=0, resources=None,
        object_store_memory=64 * 1024 * 1024,
        is_head=True, node_name="head")
    nm2 = NodeManager(
        gcs_address=gcs.address,
        session_dir=str(tmp_path / "s2"),
        num_cpus=2, num_tpus=0, resources=None,
        object_store_memory=64 * 1024 * 1024,
        is_head=False, node_name="side")
    ray_tpu.init(address=gcs.address)
    try:
        # max_restarts=-1 (unlimited): the dying node's worker-death
        # report can race its own node-death detection, burning one
        # restart on a futile same-node re-place first.
        @ray_tpu.remote(num_cpus=0, max_restarts=-1)
        class Survivor:
            def where(self):
                import os
                return os.environ.get("RAY_TPU_NODE_ID", "")

        a = Survivor.remote()
        first = ray_tpu.get(a.where.remote(), timeout=60)
        assert first == nm_head.node_id  # placed on the driver's own NM
        aid = a._actor_id.binary()
        with gcs._actor_lock:
            assert gcs._actors[aid].local_placement
        # Kill the hosting node (worker pool dies with it).
        nm_head.shutdown()
        # The GCS restarts the actor centrally on the surviving node.
        _wait_until(lambda: gcs._actors[aid].state == "ALIVE"
                    and gcs._actors[aid].node_id == nm2.node_id,
                    timeout=60, msg="actor re-placed on survivor")
        with gcs._actor_lock:
            assert not gcs._actors[aid].local_placement
        second = ray_tpu.get(a.where.remote(), timeout=60)
        assert second == nm2.node_id
    finally:
        ray_tpu.shutdown()
        for n in (nm_head, nm2):
            try:
                n.shutdown()
            except Exception:
                pass
        gcs.close()


def test_lost_placement_report_recovered_by_driver():
    """NM death before its actor_placed report reaches the GCS: the
    driver's route keeps the creation spec, and resolve_actor's 'actor
    not found' triggers a one-shot re-creation through the GCS."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker()

        # Build a creation spec the GCS never heard of (simulates the
        # lost actor_placed) and park it on the route the way
        # _try_local_create_actor does.
        class Probe:
            def ping(self):
                return "recovered"

        import cloudpickle

        from ray_tpu._private.ids import ActorID
        from ray_tpu._private.task_spec import ActorCreationSpec

        key = w.export_function(cloudpickle.dumps(Probe))
        actor_id = ActorID.of(w.job_id)
        blob, deps = w._serialize_args((), {})
        spec = ActorCreationSpec(
            actor_id=actor_id, job_id=w.job_id, class_key=key,
            args=blob, arg_deps=deps, resources={"CPU": 0.0},
            name=None, namespace=w.namespace, lifetime=None,
            max_restarts=0, max_task_retries=0, max_concurrency=1,
            is_async=False, caller_id=w.client_id,
            scheduling_strategy=None, placement_group_id=None,
            placement_group_bundle_index=-1, runtime_env=None,
            class_name="Probe", sys_path=[], trace_ctx=None)
        aid = actor_id.binary()
        route = w._route_for(aid)
        with w._actor_lock:
            route["create_spec"] = spec
            route["resolving"] = True
        # The GCS does not know this actor: the resolve path must
        # consume create_spec, re-create centrally, and resolve ALIVE.
        w._resolve_actor_route(aid)
        _wait_until(lambda: route.get("address") is not None,
                    timeout=60, msg="recovered actor resolved")
        with w._actor_lock:
            assert "create_spec" not in route  # consumed: one-shot
        refs = w.submit_actor_task(actor_id, "ping", (), {})
        assert ray_tpu.get(refs[0], timeout=60) == "recovered"
    finally:
        ray_tpu.shutdown()


def test_concurrent_create_kill_race():
    """ray.kill can reach the GCS before the NM's actor_placed report.
    The kill is tombstoned and completes when the report arrives — the
    actor must end DEAD, not leak alive forever."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        gcs = _gcs()
        nm = _own_nm()
        hold = threading.Event()
        orig = gcs._h_actor_placed

        def delayed(conn, p, msg_id):
            # Hold the placement report until the kill has landed (the
            # NM->GCS conn serve thread blocks; bounded by the test).
            hold.wait(10)
            return orig(conn, p, msg_id)

        gcs._h_actor_placed = delayed
        try:
            a = Pinger.remote()
            aid = a._actor_id.binary()
            # The NM granted locally (actor exists there), but the GCS
            # hasn't seen actor_placed yet.
            _wait_until(lambda: aid in nm._actors or aid
                        in nm._local_actor_ids,
                        msg="NM-side actor registered")
            assert aid not in gcs._actors
            ray_tpu.kill(a)   # tombstones at the GCS
            with gcs._actor_lock:
                assert aid in gcs._killed_before_placed
        finally:
            hold.set()
            gcs._h_actor_placed = orig
        _wait_until(lambda: gcs._actors.get(aid) is not None
                    and gcs._actors[aid].state == DEAD,
                    timeout=60, msg="tombstoned kill completed")
        with pytest.raises(ray_tpu.exceptions.RayActorError):
            ray_tpu.get(a.ping.remote(), timeout=30)
        # The NM's local hold drained with the worker.
        _wait_until(lambda: aid not in nm._local_actor_ids,
                    msg="NM local hold released")
    finally:
        ray_tpu.shutdown()
