"""Dynamic generator tasks (``num_returns="dynamic"``).

Modeled on the reference's generator semantics
(python/ray/tests/test_generators.py): a generator task's single return
resolves to an ObjectRefGenerator over per-yield ObjectRefs; yields are
stored as produced; a task killed mid-yield retries to a complete
generator; a raising generator surfaces the error on the generator ref.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def gen_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_dynamic_generator_basic(gen_cluster):
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    ref = gen.remote(5)
    gen_obj = ray_tpu.get(ref)
    assert isinstance(gen_obj, ray_tpu.ObjectRefGenerator)
    assert len(gen_obj) == 5
    refs = list(gen_obj)
    assert all(isinstance(r, ray_tpu.ObjectRef) for r in refs)
    assert ray_tpu.get(refs) == [i * i for i in range(5)]


def test_dynamic_generator_variable_counts(gen_cluster):
    """The yield count is data-dependent — the point of 'dynamic'."""
    @ray_tpu.remote(num_returns="dynamic")
    def split(n):
        for i in range(n):
            yield np.full(8, i)

    for n in (0, 1, 7):
        g = ray_tpu.get(split.remote(n))
        assert len(g) == n
        for i, r in enumerate(g):
            assert ray_tpu.get(r)[0] == i


def test_dynamic_generator_refs_usable_as_args(gen_cluster):
    @ray_tpu.remote(num_returns="dynamic")
    def gen():
        yield 10
        yield 20

    @ray_tpu.remote
    def add_one(x):
        return x + 1

    g = ray_tpu.get(gen.remote())
    out = ray_tpu.get([add_one.remote(r) for r in g])
    assert out == [11, 21]


def test_dynamic_generator_exception(gen_cluster):
    """A generator that raises mid-yield fails the generator ref."""
    @ray_tpu.remote(num_returns="dynamic")
    def bad():
        yield 1
        raise ValueError("mid-yield boom")

    with pytest.raises(ValueError, match="mid-yield boom"):
        ray_tpu.get(bad.remote())


def test_dynamic_generator_non_generator_return_errors(gen_cluster):
    @ray_tpu.remote(num_returns="dynamic")
    def scalar():
        return 42

    with pytest.raises(Exception):
        ray_tpu.get(scalar.remote())


def test_dynamic_generator_retry_after_kill_mid_yield(gen_cluster):
    """Killed mid-yield with retry budget: the rerun re-stores every
    index idempotently and the consumer sees ONE complete generator."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu._private import test_utils as tu

    @ray_tpu.remote(num_returns="dynamic", max_retries=2)
    def slow_gen():
        import time as _t
        for i in range(6):
            _t.sleep(0.4)
            yield i

    ref = slow_gen.remote()
    # Let a few yields land, then kill the executing worker.
    time.sleep(1.0)
    cluster = worker_mod._global_cluster
    pid = tu.kill_any_busy_worker(cluster.nm)
    assert pid is not None
    g = ray_tpu.get(ref, timeout=120)
    assert len(g) == 6
    assert ray_tpu.get(list(g)) == list(range(6))


def test_dynamic_generator_lost_yield_reconstructs():
    """Yields whose only copy lived on a dead node are rebuilt by
    re-running the producing generator task on a surviving node."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    worker_node = cluster.add_node(num_cpus=2)
    cluster.connect(object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes()
    try:
        @ray_tpu.remote(num_returns="dynamic", max_retries=2)
        def gen():
            for i in range(3):
                yield np.full(4, i)

        ref = gen.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=worker_node.node_id, soft=False)).remote()
        g = ray_tpu.get(ref)
        refs = list(g)
        cluster.remove_node(worker_node)
        vals = ray_tpu.get(refs, timeout=60)
        assert [int(v[0]) for v in vals] == [0, 1, 2]
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_data_dynamic_block_splitting(gen_cluster):
    """Data wiring: with a target block size set, read and map_batches
    tasks emit variable block counts via dynamic generator returns."""
    import ray_tpu.data as rd
    from ray_tpu.data.dataset import DataContext

    ctx = DataContext.get_current()
    ctx.target_max_rows_per_block = 10
    try:
        ds = rd.range(95, parallelism=2).map(lambda x: x + 1)
        blocks = ds._execute()
        # 2 input blocks of ~48 rows -> ceil(48/10)*2 = 10 output blocks.
        assert len(blocks) >= 8, len(blocks)
        assert sorted(ds.take_all()) == list(range(1, 96))

        import json as _json
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            p = f"{d}/rows.jsonl"
            with open(p, "w") as f:
                for i in range(37):
                    f.write(_json.dumps({"v": i}) + "\n")
            ds2 = rd.read_json(p)
            assert ds2.num_blocks() == 4   # ceil(37/10) from ONE file
            assert sorted(r["v"] for r in ds2.take_all()) == list(range(37))
    finally:
        ctx.target_max_rows_per_block = None
