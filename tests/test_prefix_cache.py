"""Prefix caching over the paged KV block pool (ISSUE 18): shared
refcounted blocks, cache-aware chunked prefill, eviction, and the
bit-identical-output contract.

The cache is a pure prefill-compute optimization: with it on or off,
every request must produce token-for-token identical output (greedy AND
sampled), and after any churn — retirement, cancel, preemption, disagg
handoff — the pool must drain to zero used and zero shared blocks.
"""

import itertools
import time

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.serve.llm import paged
from ray_tpu.serve.llm.engine import EngineConfig, InflightBatchEngine
from ray_tpu.serve.llm.paged import BlockPool
from ray_tpu.serve.llm.replicas import _build_model

BASE = dict(preset="tiny", model_overrides={"dtype": "float32"},
            max_slots=4, max_len=64, prompt_buckets=(16,),
            max_new_tokens=16)
BS = 4
N = 8


@pytest.fixture(scope="module")
def model():
    cfg, params = _build_model(EngineConfig.from_dict(BASE))
    return cfg, params


def _engine(model, prefix_cache, **kw):
    cfg, params = model
    ec = EngineConfig.from_dict(dict(
        BASE, paged_kv=True, kv_block_size=BS, prefill_chunk=BS,
        prefix_cache_enabled=prefix_cache, **kw))
    return InflightBatchEngine(params, cfg, ec)


def _run(eng, jobs):
    """Submit (prompt, seed) jobs and collect each full token stream."""
    rids = [eng.submit(p, N, seed=s) for p, s in jobs]
    return [list(itertools.chain.from_iterable(
        eng.stream(r, max_wait_s=10))) for r in rids]


def _drained(eng, timeout=10):
    deadline = time.time() + timeout
    while time.time() < deadline:
        s = eng.stats()
        if s["kv_blocks_used"] == 0 and s["busy_slots"] == 0:
            return True
        time.sleep(0.02)
    return False


# --------------------------------------------------------------- pool


def test_pool_chain_sharing_and_refcounts():
    pool = BlockPool(17, BS, prefix_cache=True)   # 16 usable
    toks = list(range(100, 116))                  # 4 full blocks

    got = pool.get_or_alloc(toks, pool.blocks_for(len(toks)))
    assert got is not None
    blocks, matched = got
    assert matched == 0 and len(blocks) == 4      # cold: all fresh
    pool.register(toks, blocks)
    assert pool.cached_blocks() == 4

    # A twin prompt shares every full block STRICTLY before its last
    # token: 16 tokens -> (16-1)//4 = 3 shared, 4th recomputed fresh.
    got2 = pool.get_or_alloc(toks, 4)
    blocks2, matched2 = got2
    assert matched2 == 3 * BS and blocks2[:3] == blocks[:3]
    assert blocks2[3] != blocks[3]
    assert pool.shared_blocks() == 3
    assert pool.stats()["kv_shared_blocks"] == 3

    # Release one side: shared blocks stay referenced by the other.
    pool.release(blocks2)
    assert pool.shared_blocks() == 0 and pool.used() == 4
    pool.release(blocks)
    # Cached blocks park on the idle LRU, NOT the free list: still
    # matchable, not "used", reclaimable on demand.
    assert pool.used() == 0 and pool.cached_blocks() == 4
    assert pool.match_prefix(toks + [1])[1] == 4 * BS


def test_eviction_lru_never_reclaims_referenced_blocks():
    pool = BlockPool(9, BS, prefix_cache=True)    # 8 usable
    hot = list(range(10, 18))                     # 2 blocks, stays held
    cold = list(range(50, 58))                    # 2 blocks, released

    hot_blocks, _ = pool.get_or_alloc(hot, 2)
    pool.register(hot, hot_blocks)
    cold_blocks, _ = pool.get_or_alloc(cold, 2)
    pool.register(cold, cold_blocks)
    pool.release(cold_blocks)                     # idle, evictable
    assert pool.available() == 4

    # Demand 6 blocks: 4 free + both idle cold blocks evicted; the
    # referenced hot chain must survive untouched.
    six = pool.alloc(6)
    assert six is not None and len(six) == 6
    assert pool.stats()["kv_prefix_evictions_total"] == 2
    assert pool.match_prefix(cold + [1])[1] == 0      # evicted
    assert pool.match_prefix(hot + [1])[1] == 2 * BS  # survived
    assert set(six).isdisjoint(hot_blocks)

    # With everything referenced, further demand fails all-or-nothing
    # rather than stealing referenced blocks.
    assert pool.alloc(1) is None
    pool.release(hot_blocks)
    assert pool.alloc(1) is not None                  # idle hot evicts


def test_pool_hash_collision_degrades_to_miss(monkeypatch):
    """All chain keys colliding must yield ZERO false matches — lookups
    verify token ids and the parent link, not just the hash."""
    monkeypatch.setattr(paged, "_chain_key",
                        lambda parent, tokens: b"same-key-always")
    pool = BlockPool(17, BS, prefix_cache=True)
    a = list(range(100, 108))
    blocks, _ = pool.get_or_alloc(a, 2)
    pool.register(a, blocks)
    # Different tokens, same (colliding) key: MISS, never a wrong block.
    assert pool.match_prefix(list(range(200, 208)) + [1]) == ([], 0)
    got = pool.get_or_alloc(list(range(200, 212)), 3)
    assert got is not None and got[1] == 0
    # The genuine twin still matches (token verification passes) —
    # though under total collision only one chain can be cached.
    assert pool.match_prefix(a + [1])[1] == BS


# ------------------------------------------------- bit-identical output


def test_bit_identical_greedy_cache_on_off(model):
    common = [7, 3, 9, 1, 4, 4, 2, 8, 6, 5, 1, 2]   # 3 full blocks
    warm = [(common + [11], 0)]
    jobs = [(common + tail, 0) for tail in
            ([12, 13], [14, 15, 16, 17], [11])]
    on, off = _engine(model, True), _engine(model, False)
    try:
        # Warm sequentially (so the prefix is registered), then a
        # concurrent wave that shares it.
        got_off = _run(off, warm) + _run(off, jobs)
        got_on = _run(on, warm) + _run(on, jobs)
        assert got_on == got_off
        s = on.stats()
        assert s["prefix_cache_enabled"] is True
        assert s["prefix_cache_hit_tokens"] > 0
        # The cache did real work: fewer prompt tokens prefilled than
        # the off engine computed.
        assert s["prefill_tokens_computed"] < \
            off.stats()["prefill_tokens_computed"]
        assert _drained(on) and _drained(off)
        assert on._pool.shared_blocks() == 0
    finally:
        on.stop()
        off.stop()


def test_bit_identical_sampled_cache_on_off(model):
    common = [5, 1, 8, 8, 2, 9, 3, 7]
    jobs = [(common + [20 + i], 100 + i) for i in range(4)] + \
        [(common + [20], 100)]                      # exact repeat too
    on = _engine(model, True, temperature=0.9, top_k=16)
    off = _engine(model, False, temperature=0.9, top_k=16)
    try:
        assert _run(on, jobs) == _run(off, jobs)
        assert on.stats()["prefix_cache_hit_tokens"] > 0
    finally:
        on.stop()
        off.stop()


def test_divergence_at_block_boundary_plus_minus_one(model):
    """Prompt pairs diverging exactly at a block boundary and one token
    to either side: outputs stay bit-identical, and the matched prefix
    never covers the divergent token (the divergence block is always
    freshly computed)."""
    base = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7]
    for div in (2 * BS - 1, 2 * BS, 2 * BS + 1):
        pair = [base[:div] + [30] + base[div:],
                base[:div] + [40] + base[div:]]
        jobs = [(p, 0) for p in pair]
        on, off = _engine(model, True), _engine(model, False)
        try:
            assert _run(on, jobs) == _run(off, jobs), div
            # Sharing is capped at the full blocks strictly before the
            # divergence point.
            assert on.stats()["prefix_cache_hit_tokens"] <= \
                (div // BS) * BS * 2
            assert _drained(on)
        finally:
            on.stop()
            off.stop()


def test_engine_collision_safety_bit_identical(model, monkeypatch):
    """Even with EVERY chain key colliding, engine output is unchanged
    — the cache degrades to misses, never to wrong KV."""
    monkeypatch.setattr(paged, "_chain_key",
                        lambda parent, tokens: b"collide")
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8, 9],
               [9, 8, 7, 6, 5, 4, 3, 2, 1],
               [1, 2, 3, 4, 5, 6, 7, 8, 9]]
    jobs = [(p, 0) for p in prompts]
    on, off = _engine(model, True), _engine(model, False)
    try:
        assert _run(on, jobs) == _run(off, jobs)
    finally:
        on.stop()
        off.stop()


# ------------------------------------------------------- leak checks


def test_preemption_churn_drains_to_zero(model):
    """Contention-driven recompute-preemption with the cache on: every
    request still gets its exact solo tokens, and the pool drains to
    zero used / zero shared blocks (no leak, no double free)."""
    cfg, params = model
    solo = _engine(model, True)
    tight = _engine(model, True, kv_num_blocks=9)   # 8 usable blocks
    try:
        common = [2, 7, 1, 8, 2, 8]
        jobs = [(common + [50 + i], i) for i in range(3)]
        expect = _run(solo, jobs)
        assert _run(tight, jobs) == expect
        assert _drained(tight)
        pool = tight._pool
        assert pool.shared_blocks() == 0
        assert not pool._refs, pool._refs
        # Every block is either free or parked idle in the cache.
        assert pool.available() + len(pool._idle) == pool.capacity
    finally:
        solo.stop()
        tight.stop()


def test_cancel_releases_shared_blocks(model):
    eng = _engine(model, True)
    try:
        warm = [6, 6, 6, 6, 1, 1, 1, 1, 3]
        _run(eng, [(warm, 0)])                      # populate the cache
        rid = eng.submit(warm[:-1] + [4], 40)       # shares 2 blocks
        deadline = time.time() + 10
        while time.time() < deadline and eng.stats()["busy_slots"] == 0:
            time.sleep(0.02)
        eng.cancel(rid)
        assert _drained(eng)
        assert eng._pool.shared_blocks() == 0
        assert not eng._pool._refs
        # The cached prefix survived the cancel and still matches.
        assert eng._pool.match_prefix(warm)[1] == 2 * BS
    finally:
        eng.stop()


def test_disagg_handoff_adopts_and_registers(model):
    """submit_prefilled on a prefix-caching pool: the adopted sequence's
    full blocks register in the chain (a later twin prompt hits them),
    suffix decode is bit-identical to the cache-off engine, and the
    handoff's blocks release cleanly at retirement."""
    from ray_tpu.models.generate import prefill_slot

    cfg, params = model
    prompt = [5, 9, 2, 11, 3, 7, 1, 4]              # 2 full blocks
    padded = jnp.zeros((1, 16), jnp.int32).at[:, :len(prompt)].set(
        jnp.asarray(prompt, jnp.int32))
    first, kv = prefill_slot(params, padded, jnp.int32(len(prompt)),
                             jnp.int32(0), cfg=cfg)
    jax.block_until_ready(kv)
    kv = {"k": kv["k"], "v": kv["v"]}

    on, off = _engine(model, True), _engine(model, False)
    try:
        outs = {}
        for eng in (on, off):
            rid = eng.submit_prefilled(int(first[0]), kv, len(prompt),
                                       N, seed=0, prompt=prompt)
            outs[eng] = list(itertools.chain.from_iterable(
                eng.stream(rid, max_wait_s=10)))
        assert outs[on] == outs[off]
        assert _drained(on)
        assert on._pool.match_prefix(prompt + [1])[1] == 2 * BS
        # A twin prompt now prefills only its suffix.
        before = on.stats()["prefix_cache_hit_tokens"]
        assert _run(on, [(prompt + [9], 0)]) == \
            _run(off, [(prompt + [9], 0)])
        assert on.stats()["prefix_cache_hit_tokens"] == before + 2 * BS
        assert _drained(on)
        assert on._pool.shared_blocks() == 0 and not on._pool._refs
    finally:
        on.stop()
        off.stop()
