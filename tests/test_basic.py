"""Core task/object API tests (modelled on the reference's
python/ray/tests/test_basic.py suite)."""

import time

import numpy as np
import pytest

import ray_tpu


def test_simple_task(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1)) == 2


def test_task_args_kwargs(ray_start_regular):
    @ray_tpu.remote
    def f(a, b=2, *, c=3):
        return a + b + c

    assert ray_tpu.get(f.remote(1)) == 6
    assert ray_tpu.get(f.remote(1, 5, c=10)) == 16


def test_put_get(ray_start_regular):
    for value in [1, "hello", {"a": [1, 2]}, None, (1, 2)]:
        assert ray_tpu.get(ray_tpu.put(value)) == value


def test_put_get_numpy(ray_start_regular):
    arr = np.random.rand(1000, 100)
    out = ray_tpu.get(ray_tpu.put(arr))
    np.testing.assert_array_equal(arr, out)


def test_object_ref_as_arg(ray_start_regular):
    @ray_tpu.remote
    def plus1(x):
        return x + 1

    ref = ray_tpu.put(10)
    assert ray_tpu.get(plus1.remote(ref)) == 11


def test_task_chain(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    ref = f.remote(0)
    for _ in range(10):
        ref = f.remote(ref)
    assert ray_tpu.get(ref) == 11


def test_nested_refs_not_resolved(ray_start_regular):
    @ray_tpu.remote
    def f(lst):
        # nested refs arrive as ObjectRefs, not values
        return [ray_tpu.get(r) for r in lst]

    refs = [ray_tpu.put(i) for i in range(3)]
    assert ray_tpu.get(f.remote(refs)) == [0, 1, 2]


def test_multiple_returns(ray_start_regular):
    @ray_tpu.remote(num_returns=3)
    def f():
        return 1, 2, 3

    a, b, c = f.remote()
    assert ray_tpu.get([a, b, c]) == [1, 2, 3]


def test_num_returns_zero(ray_start_regular):
    @ray_tpu.remote(num_returns=0)
    def f():
        return None

    assert f.remote() is None


def test_error_propagation(ray_start_regular):
    @ray_tpu.remote
    def fail():
        raise ValueError("expected failure")

    with pytest.raises(ValueError, match="expected failure"):
        ray_tpu.get(fail.remote())


def test_error_through_dependency(ray_start_regular):
    @ray_tpu.remote
    def fail():
        raise KeyError("dep failed")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(Exception):
        ray_tpu.get(consume.remote(fail.remote()))


def test_nested_tasks(ray_start_regular):
    @ray_tpu.remote
    def inner(x):
        return x * 2

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x)) + 1

    assert ray_tpu.get(outer.remote(5)) == 11


def test_wait(ray_start_regular):
    @ray_tpu.remote
    def slow(t):
        time.sleep(t)
        return t

    fast_ref = slow.remote(0.05)
    slow_ref = slow.remote(10)
    ready, not_ready = ray_tpu.wait([fast_ref, slow_ref], num_returns=1,
                                    timeout=5)
    assert ready == [fast_ref]
    assert not_ready == [slow_ref]


def test_wait_timeout(ray_start_regular):
    @ray_tpu.remote
    def hang():
        time.sleep(60)

    ready, not_ready = ray_tpu.wait([hang.remote()], timeout=0.2)
    assert ready == []
    assert len(not_ready) == 1


def test_get_timeout(ray_start_regular):
    @ray_tpu.remote
    def hang():
        time.sleep(60)

    with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
        ray_tpu.get(hang.remote(), timeout=0.3)


def test_large_object(ray_start_regular):
    arr = np.ones((4 << 20,), dtype=np.uint8)  # 4 MiB
    out = ray_tpu.get(ray_tpu.put(arr))
    assert out.nbytes == arr.nbytes


def test_large_task_arg(ray_start_regular):
    arr = np.ones((2 << 20,), dtype=np.uint8)  # 2 MiB, above inline limit

    @ray_tpu.remote
    def size_of(a):
        return a.nbytes

    assert ray_tpu.get(size_of.remote(arr)) == arr.nbytes


def test_many_parallel_tasks(ray_start_regular):
    @ray_tpu.remote
    def sq(i):
        return i * i

    refs = [sq.remote(i) for i in range(100)]
    assert ray_tpu.get(refs) == [i * i for i in range(100)]


def test_options_override(ray_start_regular):
    @ray_tpu.remote(num_returns=1)
    def f():
        return 1, 2

    a, b = f.options(num_returns=2).remote()
    assert ray_tpu.get(a) == 1 and ray_tpu.get(b) == 2


def test_cluster_resources(ray_start_regular):
    total = ray_tpu.cluster_resources()
    assert total["CPU"] == 4.0
    nodes = ray_tpu.nodes()
    assert len(nodes) == 1
    assert nodes[0]["Alive"]


def test_runtime_context(ray_start_regular):
    @ray_tpu.remote
    def ctx_info():
        ctx = ray_tpu.get_runtime_context()
        return ctx.get_task_id(), ctx.get_node_id()

    task_id, node_id = ray_tpu.get(ctx_info.remote())
    assert task_id is not None
    assert node_id == ray_tpu.nodes()[0]["NodeID"]


def test_cancel(ray_start_regular):
    @ray_tpu.remote
    def hang():
        time.sleep(60)
        return "done"

    ref = hang.remote()
    time.sleep(0.3)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(
            (ray_tpu.exceptions.TaskCancelledError,
             ray_tpu.exceptions.WorkerCrashedError,
             ray_tpu.exceptions.RayActorError)):
        ray_tpu.get(ref, timeout=10)


def test_free_objects(ray_start_regular):
    ref = ray_tpu.put("gone")
    core = ray_tpu._private.worker.require_worker()
    core.free([ref])
    time.sleep(0.2)
    assert not core.store.contains(ref.binary())
