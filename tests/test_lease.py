"""Direct task transport (worker leases) — semantics + failure paths.

Reference behaviors under test: lease reuse and pipelining
(src/ray/core_worker/transport/direct_task_transport.h:75,307), lease
return on idle, fallback to the scheduled path on worker death, and the
GCS-side resource accounting for held leases.
"""

import time

import pytest

import ray_tpu


@pytest.fixture
def lease_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _lease_mgr():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker()._lease_mgr


def test_lease_reuse_same_worker(lease_cluster):
    """Sequential same-shape tasks reuse one leased worker (one pid)."""
    import os as _os  # noqa: F401

    @ray_tpu.remote
    def pid():
        import os
        return os.getpid()

    pids = {ray_tpu.get(pid.remote()) for _ in range(10)}
    assert len(pids) == 1, pids
    lm = _lease_mgr()
    assert lm is not None
    key = (("CPU", 1.0),)
    assert key in lm._shapes and len(lm._shapes[key].leases) >= 1


def test_lease_results_and_errors(lease_cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    assert ray_tpu.get([sq.remote(i) for i in range(100)]) == \
        [i * i for i in range(100)]

    @ray_tpu.remote
    def boom():
        raise ValueError("lease boom")

    with pytest.raises(ValueError, match="lease boom"):
        ray_tpu.get(boom.remote())


def test_lease_dep_chain(lease_cluster):
    """ObjectRef args between lease tasks resolve (and stay pinned)."""
    @ray_tpu.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(10):
        ref = inc.remote(ref)
    assert ray_tpu.get(ref) == 11


def test_lease_idle_return_releases_resources(lease_cluster):
    """After the idle timeout, leases are returned and the GCS resource
    view recovers to full capacity."""
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(20)])
    lm = _lease_mgr()
    deadline = time.time() + float(
        __import__("ray_tpu._private.config",
                   fromlist=["config"]).config.lease_idle_timeout_s) + 6
    while time.time() < deadline:
        if not any(st.leases for st in lm._shapes.values()):
            break
        time.sleep(0.2)
    assert not any(st.leases for st in lm._shapes.values())
    # The GCS view recovers via the NM's ASYNC resource reports (eager
    # push on release edges + heartbeats): poll, bounded, instead of
    # racing the two notify hops.
    deadline = time.time() + 10
    avail = {}
    while time.time() < deadline:
        avail = ray_tpu.available_resources()
        if avail.get("CPU", 0) == 4.0:
            break
        time.sleep(0.2)
    assert avail.get("CPU", 0) == 4.0, avail


def test_lease_worker_death_falls_back(lease_cluster):
    """Killing the leased worker mid-task: the spec falls back to the
    scheduled path and still completes (at-least-once, like task retry)."""
    @ray_tpu.remote(max_retries=2)
    def slow_pid(sec):
        import os
        import time as _t
        _t.sleep(sec)
        return os.getpid()

    # Warm a lease, find its worker pid.
    pid0 = ray_tpu.get(slow_pid.remote(0.0))
    ref = slow_pid.remote(3.0)
    time.sleep(0.5)   # task is now running on the leased worker
    import os
    import signal
    os.kill(pid0, signal.SIGKILL)
    # The lease conn drops; the spec is resubmitted via the GCS.
    pid1 = ray_tpu.get(ref, timeout=60)
    assert pid1 != pid0


def test_lease_capacity_denial_falls_back(lease_cluster):
    """More parallel tasks than CPUs: overflow runs via the scheduled
    path (lease requests denied at capacity) and everything completes."""
    @ray_tpu.remote
    def busy(x):
        import time as _t
        _t.sleep(0.1)
        return x

    out = ray_tpu.get([busy.remote(i) for i in range(40)], timeout=90)
    assert out == list(range(40))


def test_lease_cancel(lease_cluster):
    @ray_tpu.remote
    def forever():
        import time as _t
        _t.sleep(600)

    ref = forever.remote()
    time.sleep(0.6)   # let it reach the leased worker
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.exceptions.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)


def test_lease_objects_visible_to_other_clients(lease_cluster):
    """Locations flushed to the GCS: an actor (separate process) can get
    an object produced by the driver's lease task."""
    @ray_tpu.remote
    def make():
        return {"k": 41}

    ref = make.remote()

    @ray_tpu.remote
    class Reader:
        def read(self, r):
            return r["k"] + 1

    reader = Reader.remote()
    assert ray_tpu.get(reader.read.remote(ref)) == 42


def test_lease_disabled_still_works(monkeypatch):
    """The classic path is intact when leases are off."""
    monkeypatch.setenv("RAY_TPU_LEASE_ENABLED", "0")
    from ray_tpu._private.config import config
    config.set("lease_enabled", False)
    try:
        ctx = ray_tpu.init(num_cpus=2,
                           object_store_memory=64 * 1024 * 1024)

        @ray_tpu.remote
        def sq(x):
            return x * x

        assert ray_tpu.get([sq.remote(i) for i in range(10)]) == \
            [i * i for i in range(10)]
        from ray_tpu._private import worker as worker_mod
        assert worker_mod.global_worker()._lease_mgr is None
    finally:
        ray_tpu.shutdown()
        config.set("lease_enabled", True)


def test_lease_force_cancel_kills_worker(lease_cluster):
    """force=True on a lease task kills the worker process (classic
    force-cancel semantics) and the ref resolves to TaskCancelledError,
    never a silent hang or a resubmission."""
    @ray_tpu.remote
    def stuck():
        import time as _t
        _t.sleep(600)

    ref = stuck.remote()
    deadline = time.time() + 30
    lm = _lease_mgr()
    while time.time() < deadline:   # wait until it's running on a lease
        if ref.task_id().binary() in lm._task_lease:
            break
        time.sleep(0.1)
    time.sleep(0.5)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises((ray_tpu.exceptions.TaskCancelledError,
                        ray_tpu.exceptions.WorkerCrashedError)):
        ray_tpu.get(ref, timeout=30)


def test_lease_fairness_actor_not_starved(lease_cluster):
    """Sustained lease traffic saturating every CPU must not starve the
    classic path: an actor created mid-stream still comes up (GCS denies
    new leases and revokes held ones under classic-queue pressure)."""
    @ray_tpu.remote
    def busy(x):
        import time as _t
        _t.sleep(0.05)
        return x

    stream = [busy.remote(i) for i in range(120)]   # > 4 CPUs of work

    @ray_tpu.remote
    class Probe:
        def ping(self):
            return "up"

    probe = Probe.remote()
    assert ray_tpu.get(probe.ping.remote(), timeout=60) == "up"
    assert ray_tpu.get(stream, timeout=120) == list(range(120))


def test_lease_revoke_drains_without_double_execution(lease_cluster, tmp_path):
    """Fairness revocation is a policy decision, not a failure: tasks
    already in flight on the (healthy) revoked worker run EXACTLY once,
    a max_retries=0 task sees no spurious WorkerCrashedError, and the
    worker is surrendered only after its batch drains."""
    marker = tmp_path / "runs.txt"

    @ray_tpu.remote(max_retries=0)
    def side_effect(path, sec):
        import time as _t
        with open(path, "a") as f:
            f.write("ran\n")
        _t.sleep(sec)
        return "done"

    # Warm the lease, then put a slow side-effecting task in flight.
    assert ray_tpu.get(side_effect.remote(str(marker), 0.0)) == "done"
    ref = side_effect.remote(str(marker), 2.0)
    lm = _lease_mgr()
    key = (("CPU", 1.0),)
    deadline = time.time() + 10
    while time.time() < deadline:
        st = lm._shapes.get(key)
        if st and any(l.pending for l in st.leases):
            break
        time.sleep(0.05)
    st = lm._shapes.get(key)
    lease = next(l for l in st.leases if l.pending)
    lm.revoke(lease.lease_id)
    # No WorkerCrashedError, no re-execution.
    assert ray_tpu.get(ref, timeout=30) == "done"
    assert marker.read_text().count("ran") == 2  # warm-up + the one task
    # The drained lease is eventually dropped (worker surrendered).
    deadline = time.time() + 15
    while time.time() < deadline:
        if lease not in (lm._shapes.get(key).leases
                         if lm._shapes.get(key) else []):
            break
        time.sleep(0.1)
    st = lm._shapes.get(key)
    assert st is None or lease not in st.leases


def test_infeasible_queued_task_does_not_block_leases(lease_cluster):
    """A permanently-unplaceable queued task (typo'd resource) must not
    deny lease grants or thrash healthy leases: CPU tasks keep the
    direct transport (reference keeps infeasible tasks in a separate
    non-blocking queue)."""
    @ray_tpu.remote(resources={"no_such_resource": 1})
    def never():
        return None

    _parked = never.remote()   # queues in the GCS forever  # noqa: F841

    @ray_tpu.remote
    def pid():
        import os
        return os.getpid()

    time.sleep(0.5)   # let the infeasible spec reach the GCS queue
    pids = {ray_tpu.get(pid.remote()) for _ in range(10)}
    assert len(pids) == 1, pids   # direct transport engaged + stable
    lm = _lease_mgr()
    key = (("CPU", 1.0),)
    st = lm._shapes.get(key)
    assert st is not None and any(not l.dead for l in st.leases)


def test_lease_grants_are_local_first(lease_cluster):
    """With local scheduling on (default), steady-state leases come from
    the caller's own node manager (lease.local), and the grant-latency
    histogram records them under source="local"."""
    @ray_tpu.remote
    def nop():
        return None

    ray_tpu.get([nop.remote() for _ in range(10)])
    lm = _lease_mgr()
    leases = [l for st in lm._shapes.values() for l in st.leases]
    assert leases and any(l.local for l in leases)
    from ray_tpu._private.lease import _grant_latency_hist
    assert any(name.endswith("_count") and tags.get("source") == "local"
               and value >= 1
               for name, tags, value in _grant_latency_hist().samples())


def test_lease_fast_result_not_stuck_behind_slow(lease_cluster):
    """A fast task's result must reach the caller promptly even when a
    long task runs right behind it on the same leased worker (results
    may never buffer across the next task's execution)."""
    @ray_tpu.remote
    def job(t):
        import time as _t
        _t.sleep(t)
        return t

    fast = job.remote(0.05)
    slow = job.remote(20)
    t0 = time.time()
    ready, not_ready = ray_tpu.wait([fast, slow], num_returns=1, timeout=10)
    assert ready == [fast] and not_ready == [slow]
    assert time.time() - t0 < 5
    ray_tpu.cancel(slow, force=True)
