"""Train library tests: DP training with gradient allreduce, checkpoint
persistence, and gang restart from checkpoint on worker failure."""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint, DataParallelTrainer, FailureConfig, RunConfig, ScalingConfig,
)


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _dp_mlp_loop(config):
    """2-worker data-parallel MLP: grads allreduced through the session's
    collective group; rank 0 reports + checkpoints."""
    import jax
    import jax.numpy as jnp
    from ray_tpu import train
    from ray_tpu.models import MLPConfig, mlp_forward, mlp_init

    rank, ws = train.get_world_rank(), train.get_world_size()
    cfg = MLPConfig(in_dim=8, hidden=(16,), out_dim=2)
    params = mlp_init(jax.random.key(0), cfg)

    rng = np.random.default_rng(100 + rank)  # per-rank data shard
    x = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, size=(16,)))

    def loss_fn(p):
        logits = mlp_forward(p, x)
        logz = jax.nn.logsumexp(logits, axis=-1)
        return jnp.mean(logz - jnp.take_along_axis(
            logits, y[:, None], axis=1)[:, 0])

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    lr = config["lr"]
    for step in range(config["steps"]):
        loss, grads = grad_fn(params)
        flat, treedef = jax.tree.flatten(grads)
        flat = [np.asarray(train.session.allreduce(np.asarray(g))) / ws
                for g in flat]
        grads = jax.tree.unflatten(treedef, flat)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        if rank == 0:
            ckpt = None
            if step == config["steps"] - 1:
                ckpt = Checkpoint.from_pytree(params,
                                              extra={"step": step})
            train.report({"loss": float(loss), "step": step},
                         checkpoint=ckpt)


def test_data_parallel_training(ray_4cpu, tmp_path):
    trainer = DataParallelTrainer(
        _dp_mlp_loop,
        train_loop_config={"steps": 4, "lr": 0.5},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="dp_mlp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert len(result.metrics_history) == 4
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]
    # checkpoint persisted under the run dir and restorable
    assert result.checkpoint is not None
    assert result.checkpoint.path.startswith(str(tmp_path))
    restored = result.checkpoint.to_pytree()
    assert "layers" in restored
    assert result.checkpoint.to_dict()["step"] == 3


def _flaky_loop(config):
    import jax
    from ray_tpu import train
    from ray_tpu.models import MLPConfig, mlp_init

    marker = config["marker"]
    start_step = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start_step = ckpt.to_dict()["step"] + 1

    params = mlp_init(jax.random.key(0), MLPConfig(in_dim=4, hidden=(8,),
                                                   out_dim=2))
    for step in range(start_step, config["steps"]):
        if step == 2 and not os.path.exists(marker):
            open(marker, "w").write("crashed")
            raise RuntimeError("injected failure at step 2")
        train.report({"step": step},
                     checkpoint=Checkpoint.from_dict({"step": step}))


def test_failure_restart_from_checkpoint(ray_4cpu, tmp_path):
    marker = str(tmp_path / "crash_marker")
    trainer = DataParallelTrainer(
        _flaky_loop,
        train_loop_config={"steps": 5, "marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="flaky", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    assert os.path.exists(marker)  # it did crash once
    steps = [m["step"] for m in result.metrics_history]
    # steps 0,1 from attempt 1, then resumed at 2 (not 0) after restart
    assert steps == [0, 1, 2, 3, 4]


def test_num_to_keep_pruning_survives_restart(ray_4cpu, tmp_path):
    """Checkpoint retention is enforced across gang restarts: _drive
    rebuilds its kept-list from run_dir, so earlier attempts' checkpoints
    still count against num_to_keep."""
    from ray_tpu.train import CheckpointConfig

    marker = str(tmp_path / "crash_marker2")
    trainer = DataParallelTrainer(
        _flaky_loop,
        train_loop_config={"steps": 6, "marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="prune", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
            checkpoint_config=CheckpointConfig(num_to_keep=2)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    run_dir = str(tmp_path / "prune")
    ckpts = [d for d in os.listdir(run_dir) if d.startswith("checkpoint_")]
    assert len(ckpts) <= 2, ckpts


def test_failure_exhausts_retries(ray_4cpu, tmp_path):
    def always_fails(config):
        raise ValueError("boom")

    trainer = DataParallelTrainer(
        always_fails, train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="fails", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert not result.ok
    assert "boom" in str(result.error)


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    ck = Checkpoint.from_dict({"a": 1}, path=str(tmp_path / "c1"))
    assert ck.to_dict() == {"a": 1}

    tree = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.zeros((3,))}
    ck2 = Checkpoint.from_pytree(tree, path=str(tmp_path / "c2"),
                                 extra={"step": 7})
    out = ck2.to_pytree()
    np.testing.assert_allclose(np.asarray(out["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert ck2.to_dict()["step"] == 7
    moved = ck2.move_to(str(tmp_path / "c3"))
    assert moved.to_dict()["step"] == 7


def _torch_ddp_loop(config):
    """2-worker torch DP: bucketed backward_allreduce must produce the
    average of the ranks' gradients on every parameter (VERDICT r3 weak
    #7: one collective per <=25MB bucket, not per parameter)."""
    import torch

    from ray_tpu import train
    from ray_tpu.train import torch as rt_torch

    rank = train.get_world_rank()
    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 2))
    model = rt_torch.prepare_model(model)

    x = torch.full((4, 8), float(rank + 1))
    loss = model(x).sum()
    loss.backward()
    # Expected average: recompute both ranks' grads locally.
    expected = {}
    ref = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.ReLU(), torch.nn.Linear(16, 2))
    ref.load_state_dict(model.state_dict())
    for other in (1.0, 2.0):
        ref.zero_grad()
        ref(torch.full((4, 8), other)).sum().backward()
        for n, p in ref.named_parameters():
            expected[n] = expected.get(n, 0) + p.grad.detach().clone() / 2

    rt_torch.backward_allreduce(model, bucket_cap_bytes=256)  # many buckets
    for n, p in model.named_parameters():
        assert torch.allclose(p.grad, expected[n], atol=1e-5), n
    train.report({"ok": 1.0, "rank": rank})


def test_torch_bucketed_allreduce(ray_4cpu, tmp_path):
    from ray_tpu.train.torch import TorchTrainer

    trainer = TorchTrainer(
        _torch_ddp_loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["ok"] == 1.0
