"""Worker-turnaround fast path: in-band small-object returns, batched
completions, and the elastic worker pool (``_private/inline_objects.py``
+ worker_main/_h_task_done_batch plumbing).

The contract under test (ISSUE 14 acceptance):

* a sub-threshold result touches the object store ZERO times — the blob
  rides the completion message end to end (probe: the node-wide store
  object count does not move);
* the threshold is exact (framed size == knob inlines; one byte over
  takes the store path) and device arrays ALWAYS take the store path
  (their pickle-5 out-of-band buffers make them inline-ineligible);
* GCS inline-table pressure materializes entries into a real store and
  ``get()`` results stay bit-identical across the spill;
* a worker dying between batch-buffered completions re-executes the
  task (at-least-once) and duplicate completion records are idempotent
  at the GCS (dedup);
* ``ray.get`` of an inline ERROR return raises the original exception,
  and an N-return failure aliases ONE serialized blob across all ids;
* the shared CPU pool grows under queue-depth pressure (within
  ``num_workers_soft_limit``) and shrinks back when idle.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import inline_objects, serialization
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import JobID, ObjectID, TaskID
from ray_tpu._private.task_spec import TaskSpec


def _cluster(**system_config):
    return ray_tpu.init(num_cpus=2,
                        object_store_memory=128 * 1024 * 1024,
                        _system_config=system_config or None)


@pytest.fixture
def ray_cluster():
    ctx = _cluster()
    yield ctx
    ray_tpu.shutdown()


def _store_objects() -> int:
    return worker_mod.global_worker().store.stats()["num_objects"]


# ------------------------------------------------- zero-plasma fast path


def test_inline_roundtrip_zero_store_puts(ray_cluster):
    @ray_tpu.remote
    def nop():
        return 41

    assert ray_tpu.get(nop.remote(), timeout=60) == 41   # warm the lease
    before = _store_objects()
    refs = [nop.remote() for _ in range(40)]
    assert ray_tpu.get(refs, timeout=60) == [41] * 40
    assert _store_objects() == before, \
        "sub-threshold results must never touch the store"


def test_inline_result_feeds_downstream_task(ray_cluster):
    @ray_tpu.remote
    def produce():
        return {"k": 41}

    @ray_tpu.remote
    def consume(d):
        return d["k"] + 1

    assert ray_tpu.get(consume.remote(produce.remote()), timeout=60) == 42


# ------------------------------------------------- threshold boundary ±1


_PAYLOAD = b"p" * 512


def _framed_size(value) -> int:
    return serialization.serialize(value).total_size()


@pytest.mark.parametrize("delta,expect_inline", [(0, True), (-1, False)])
def test_inline_threshold_boundary(delta, expect_inline):
    size = _framed_size(_PAYLOAD)
    _cluster(worker_inline_return_max=size + delta)
    try:
        @ray_tpu.remote
        def pay():
            return _PAYLOAD

        assert ray_tpu.get(pay.remote(), timeout=60) == _PAYLOAD  # warm
        before = _store_objects()
        refs = [pay.remote() for _ in range(5)]
        assert ray_tpu.get(refs, timeout=60) == [_PAYLOAD] * 5
        grew = _store_objects() - before
        if expect_inline:
            assert grew == 0, "at-threshold result must inline"
        else:
            assert grew >= 5, "one-byte-over result must take the store"
    finally:
        ray_tpu.shutdown()


def test_device_objects_always_store_path(ray_cluster):
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    @ray_tpu.remote
    def mk():
        import jax.numpy as jnp

        return jnp.arange(16, dtype=jnp.float32)

    ref = mk.remote()
    back = ray_tpu.get(ref, timeout=120)
    assert isinstance(back, jax.Array)
    np.testing.assert_array_equal(
        np.asarray(back), np.asarray(jnp.arange(16, dtype=jnp.float32)))
    # Tiny (64 data bytes) yet store-resident: out-of-band buffers make
    # device arrays inline-ineligible regardless of size.
    assert worker_mod.global_worker().store.contains(ref.binary())


# ------------------------------------------------------- error returns


def test_get_of_inline_error_raises_original(ray_cluster):
    class Boom(ValueError):
        pass

    @ray_tpu.remote(num_returns=3)
    def fail():
        raise ValueError("original message")

    a, b, c = fail.remote()
    before = _store_objects()
    for ref in (a, b, c):
        with pytest.raises(ValueError, match="original message"):
            ray_tpu.get(ref, timeout=60)
    assert _store_objects() == before, \
        "a small error return must inline, not store"


def test_error_blob_aliased_across_return_ids():
    """_store_error_returns serializes ONCE and aliases the same bytes
    object across every return id (the completion pickle memoizes it,
    so an N-return failure ships one copy)."""
    from ray_tpu import exceptions
    from ray_tpu._private.worker_main import WorkerExecutor

    ex = object.__new__(WorkerExecutor)
    ex._inline_max = 8192
    spec = TaskSpec(task_id=TaskID.for_task(JobID.from_int(1)),
                    job_id=JobID.from_int(1), function_key="k",
                    args=b"", arg_deps=[], num_returns=4,
                    resources={"CPU": 1})
    err = exceptions.RayTaskError("f", "boom")
    objects, inline = ex._store_error_returns(spec, err)
    assert len(objects) == 4 and len(inline) == 4
    blobs = list(inline.values())
    assert all(b is blobs[0] for b in blobs), \
        "every return id must alias ONE serialized blob"
    back = serialization.loads_oob(blobs[0])
    assert isinstance(back, exceptions.RayTaskError)


# ------------------------------------------- table pressure spill


def test_inline_table_pressure_spill_bit_identical():
    # ~1.2 KiB per result against a 4 KiB per-job table: most results
    # must materialize into the store, and get() must not notice.
    _cluster(gcs_inline_table_bytes=4096)
    try:
        @ray_tpu.remote
        def pay(i):
            return bytes([i % 256]) * 1200

        refs = [pay.remote(i) for i in range(24)]
        vals = ray_tpu.get(refs, timeout=120)
        assert vals == [bytes([i % 256]) * 1200 for i in range(24)]
        # The table settles under its per-job budget once the spills'
        # store copies confirm (keep-until-confirmed is async).
        w = worker_mod.global_worker()
        deadline = time.time() + 30
        while time.time() < deadline:
            stats = w.gcs.request("control_plane_stats", timeout=30)
            if stats["inline_bytes"] <= 4096:
                break
            time.sleep(0.2)
        assert stats["inline_bytes"] <= 4096
        # Spilled results are REAL store objects now — still readable.
        vals2 = ray_tpu.get(refs, timeout=120)
        assert vals2 == vals, "spill must preserve results bit-identically"
    finally:
        ray_tpu.shutdown()


# --------------------------------------- redelivery + GCS-side dedup


def test_duplicate_completion_batch_is_idempotent(ray_cluster):
    """At-least-once delivery: the same task_done_batch frame applied
    twice (worker died after the NM relayed but before the ack-side
    bookkeeping, NM retried) must leave one consistent copy."""
    import pickle

    gcs = worker_mod._global_cluster.gcs
    assert gcs is not None, "test requires the in-process GCS"
    w = worker_mod.global_worker()
    tid = TaskID.for_task(w.job_id)
    oid = ObjectID.for_return(tid, 0).binary()
    blob = serialization.serialize("dup-value").to_bytes()
    rec = {"task_id": tid.binary(), "status": "ok",
           "objects": [(oid, len(blob))], "inline": {oid: blob},
           "error": None}
    frame = {"node_id": w.node_id, "blobs": [pickle.dumps(rec, protocol=5)]}
    gcs._h_task_done_batch(None, frame, 0)
    gcs._h_task_done_batch(None, frame, 0)   # duplicate delivery
    assert gcs._inline_tbl.get(oid) == blob
    assert ray_tpu.get(worker_mod.ObjectRef(ObjectID(oid)),
                       timeout=30) == "dup-value"


def test_worker_death_between_batched_completions():
    """Kill the executing pool worker mid-burst: buffered-but-unflushed
    completions die with it, the NM reports the in-flight tasks crashed,
    the GCS retries, and every get() still resolves correctly (any
    double-landed completion is idempotent at the GCS)."""
    _cluster()
    try:
        @ray_tpu.remote(max_retries=4)
        def slow(i):
            time.sleep(0.05)
            return i * 3

        nm = worker_mod._global_cluster.nm
        refs = [slow.remote(i) for i in range(30)]
        time.sleep(0.4)   # let the burst start executing
        with nm._lock:
            victims = [x for x in nm._workers.values()
                       if x.current_tasks and x.proc.poll() is None]
        for v in victims[:1]:
            try:
                os.kill(v.proc.pid, 9)
            except OSError:
                pass
        vals = ray_tpu.get(refs, timeout=120)
        assert vals == [i * 3 for i in range(30)]
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------- elastic worker pool


def test_elastic_pool_grows_and_shrinks():
    ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024,
                 _system_config={"num_workers_soft_limit": 5,
                                "worker_idle_timeout_s": 1.0,
                                "lease_enabled": 0,
                                "local_scheduling_enabled": 0})
    try:
        nm = worker_mod._global_cluster.nm

        def pool_size():
            with nm._lock:
                return len([x for x in nm._workers.values()
                            if not x.dedicated and x.state != "dead"
                            and x.proc.poll() is None])

        @ray_tpu.remote(num_cpus=0)
        def hold():
            time.sleep(0.6)
            return 1

        refs = [hold.remote() for _ in range(8)]
        peak = pool_size()
        deadline = time.time() + 20
        while time.time() < deadline and peak < 4:
            peak = max(peak, pool_size())
            time.sleep(0.05)
        assert peak >= 4, \
            f"queue pressure should grow the pool past its base (got {peak})"
        assert peak <= 5, "growth must respect num_workers_soft_limit"
        assert sum(ray_tpu.get(refs, timeout=120)) == 8
        # Idle shrink: back to the base pool within the idle timeout
        # (+ reaper cadence headroom).
        deadline = time.time() + 20
        while time.time() < deadline and pool_size() > nm._max_pool:
            time.sleep(0.2)
        assert pool_size() <= nm._max_pool, \
            "idle workers above the base pool must retire"
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------ cache-pressure paths


def test_inline_cache_disabled_still_resolves():
    """With the local inline cache off, every get() falls back to the
    GCS table (object_locations carries the blob) — slower, never
    wrong."""
    _cluster(worker_inline_cache_bytes=0)
    try:
        @ray_tpu.remote
        def nop(i):
            return ("v", i)

        refs = [nop.remote(i) for i in range(10)]
        assert ray_tpu.get(refs, timeout=60) == [("v", i)
                                                 for i in range(10)]
    finally:
        ray_tpu.shutdown()


def test_inline_eligibility_unit():
    small = serialization.serialize(41)
    assert inline_objects.eligible(small, 8192)
    assert not inline_objects.eligible(small, 0)
    assert not inline_objects.eligible(
        small, small.total_size() - 1)
    np = pytest.importorskip("numpy")
    oob = serialization.serialize(np.zeros(8, dtype=np.float32))
    if oob.buffers:   # numpy rides out-of-band under protocol 5
        assert not inline_objects.eligible(oob, 1 << 20)


def test_inline_table_insert_evicts_oldest_of_same_job():
    tbl = inline_objects.InlineTable(per_job_bytes=1000)
    job_a, job_b = b"A", b"B"
    spills = tbl.insert(b"o1", b"x" * 600, job_a, "n1")
    assert spills == []
    spills = tbl.insert(b"o2", b"y" * 600, job_a, "n1")
    assert [s[0] for s in spills] == [b"o1"], \
        "over-budget insert must select the job's oldest entry"
    # Job B has its own budget.
    assert tbl.insert(b"o3", b"z" * 600, job_b, "n2") == []
    # Keep-until-confirmed: the selected entry is still readable...
    assert tbl.get(b"o1") == b"x" * 600
    # ...until the store copy confirms and the caller drops it.
    assert tbl.drop(b"o1")
    assert tbl.get(b"o1") is None
    n, total = tbl.stats()
    assert n == 2 and total == 1200


def test_completion_not_held_behind_slow_successor():
    """The slack flusher bounds how long a finished fast task's result
    can sit buffered behind a slow successor on the same worker: with
    ONE pool worker, fast() completes, slow() starts executing, and the
    fast result must still arrive within the flush slack — not after
    slow() finishes (the run loop no longer flushes inline before each
    task; the rtpu-completion-flush thread owns the bound)."""
    ray_tpu.init(num_cpus=1,
                 object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote
        def fast():
            return "fast"

        @ray_tpu.remote
        def slow():
            time.sleep(4.0)
            return "slow"

        ray_tpu.get(fast.remote(), timeout=60)   # warm the worker
        f = fast.remote()
        s = slow.remote()
        t0 = time.perf_counter()
        assert ray_tpu.get(f, timeout=10) == "fast"
        waited = time.perf_counter() - t0
        assert waited < 2.0, (
            f"fast result waited {waited:.2f}s — held behind slow()")
        assert ray_tpu.get(s, timeout=30) == "slow"
    finally:
        ray_tpu.shutdown()


def test_inline_table_pressure_sweep_reselects_lost_spills():
    """A store_inline_objects notify lost in flight must be re-sent by
    the periodic pressure sweep: insert() only re-selects when the same
    job inserts again, so a job that went quiet after a lost notify
    would otherwise hold its over-budget bytes forever."""
    tbl = inline_objects.InlineTable(per_job_bytes=1000)
    assert tbl.insert(b"o1", b"x" * 600, b"J", "n1") == []
    first = tbl.insert(b"o2", b"y" * 600, b"J", "n1")
    assert [s[0] for s in first] == [b"o1"]
    # Within the retry window the in-flight spill is not re-sent...
    assert tbl.pressure_spills() == []
    # ...but once it goes stale (lost notify), the sweep re-selects it.
    tbl._spilling[b"o1"] -= inline_objects.InlineTable.SPILL_RETRY_S + 1
    assert [s[0] for s in tbl.pressure_spills()] == [b"o1"]
    # Confirmation drops it; an under-budget job has nothing to spill.
    assert tbl.drop(b"o1")
    assert tbl.pressure_spills() == []


def test_free_mid_spill_late_confirm_deletes_not_resurrects():
    """free() racing an in-flight pressure spill: the spill target is
    not in the directory yet (keep-until-confirmed), so the free's
    delete fan-out misses it — the late add_object_locations confirm
    must queue a delete for the freed store copy instead of
    re-registering a location that would leak it forever."""
    from ray_tpu._private.gcs import GcsServer
    gcs = GcsServer()
    try:
        tbl = gcs._inline_tbl
        tbl._budget = 1000
        job = b"J"
        o1, o2, o3 = b"a" * 28, b"b" * 28, b"c" * 28
        with gcs._obj_lock:
            assert tbl.insert(o1, b"x" * 600, job, "nodeX") == []
            gcs._obj_locations[o1].add(inline_objects.INLINE_LOCATION)
            spills = tbl.insert(o2, b"y" * 600, job, "nodeX")
            gcs._obj_locations[o2].add(inline_objects.INLINE_LOCATION)
        assert [s[0] for s in spills] == [o1]   # o1 materialization in flight
        with gcs._obj_lock:
            gcs._free_now([o1])
        assert o1 in gcs._freed_mid_spill
        with gcs._sched_lock, gcs._obj_lock:
            assert gcs._add_location_obj_quiet(o1, "nodeX", 600) == []
        assert o1 not in gcs._obj_locations, "freed object resurrected"
        assert gcs._deferred_deletes.get("nodeX") == [o1]
        assert o1 not in gcs._freed_mid_spill   # tombstone consumed
        # An unrelated fresh object on the same node registers normally.
        with gcs._sched_lock, gcs._obj_lock:
            gcs._add_location_obj_quiet(o3, "nodeX", 10)
        assert "nodeX" in gcs._obj_locations[o3]
        # Re-targeted spill (producer dead, sent to another live node):
        # the tombstone must follow the REAL target or the fallback
        # node's confirm bypasses it.
        tbl._spilling[o2] = time.monotonic()   # select o2's spill
        assert tbl.spill_inflight(o2) == "nodeX"
        assert tbl.note_spill_target(o2, "nodeY")
        assert tbl.spill_inflight(o2) == "nodeY"
        with gcs._obj_lock:
            gcs._free_now([o2])
        assert gcs._freed_mid_spill[o2][0] == "nodeY"
        with gcs._sched_lock, gcs._obj_lock:
            assert gcs._add_location_obj_quiet(o2, "nodeY", 600) == []
        assert o2 not in gcs._obj_locations
        assert o2 in gcs._deferred_deletes.get("nodeY", [])
    finally:
        gcs.close()


def test_wait_pops_resolved_pending_returns(ray_cluster):
    """wait() must retire resolved oids from the pending-returns
    window: a poll loop re-waiting on a completed ref otherwise pays
    the GCS wait_for_objects round trip on every iteration forever
    (the window entry shadows the local store probe)."""
    w = worker_mod.global_worker()

    @ray_tpu.remote
    def f():
        return 1

    ref = f.remote()
    ready, rest = ray_tpu.wait([ref], timeout=30)
    assert ready and not rest
    assert ref._id.binary() not in w._pending_returns


def test_wait_stops_probing_after_num_returns_satisfied(ray_cluster):
    """wait(num_returns=k) must stop scanning once k refs are ready:
    the result only takes the first k ready refs, so probing the rest
    re-pays a ctypes store.contains per ref on every poll iteration
    for refs the caller already collected (SCALE_r10 small fix)."""
    w = worker_mod.global_worker()
    refs = [ray_tpu.put(i) for i in range(16)]
    calls = []
    real = w.store.contains

    def counting(oid):
        calls.append(oid)
        return real(oid)

    w.store.contains = counting
    try:
        ready, rest = ray_tpu.wait(refs, num_returns=1, timeout=10)
    finally:
        w.store.contains = real
    assert len(ready) == 1 and len(rest) == 15
    # fetch_local may legitimately re-probe the ONE ready ref; the scan
    # must not have touched the other fifteen.
    assert len(set(calls)) <= 1, \
        f"scanned past num_returns: {len(set(calls))} distinct probes"


def test_pool_pressure_ignores_chip_starved_tpu_specs():
    """A queue holding only TPU specs waiting for chips must not grow
    the shared CPU pool: a pool worker spawned for them could never run
    them, and each dispatch pass would ramp the pool to its cap."""
    from ray_tpu._private.node_manager import NodeManager

    class _Spec:
        def __init__(self, res):
            self.resources = res

    class _Stub:
        _workers = {}
        _pool_cap = 8
        _task_queue = [_Spec({"TPU": 4.0})]

    assert not NodeManager._pool_pressure_locked(_Stub())
    _Stub._task_queue.append(_Spec({"CPU": 1.0}))
    assert NodeManager._pool_pressure_locked(_Stub())


def test_failed_report_flush_requeues_inline_blobs(ray_cluster):
    """A lease_task_events notify failure must RE-QUEUE the completion
    reports: with inline returns the report carries the only durable
    copy of the value — dropping it would turn a transient GCS hiccup
    into data loss once the driver's inline cache churns."""
    w = worker_mod.global_worker()

    @ray_tpu.remote
    def f():
        return "requeue-me"

    assert ray_tpu.get(f.remote(), timeout=60) == "requeue-me"  # warm lease
    lm = w._lease_mgr
    real_notify = w.gcs.notify
    dropped = {"n": 0}

    def flaky(verb, payload=None, **kw):
        if verb == "lease_task_events":
            dropped["n"] += 1
            raise ConnectionError("injected GCS hiccup")
        return real_notify(verb, payload, **kw)

    w.gcs.notify = flaky
    try:
        ref = f.remote()
        # In-band delivery serves the local get regardless of the GCS.
        assert ray_tpu.get(ref, timeout=30) == "requeue-me"
        deadline = time.time() + 10
        while dropped["n"] == 0 and time.time() < deadline:
            lm._flush_reports()
            time.sleep(0.01)
        assert dropped["n"] >= 1
        requeued = False
        for _ in range(200):
            with lm._lock:
                if lm._reports:
                    requeued = True
                    break
            time.sleep(0.01)
        assert requeued, "failed lease report was dropped, not re-queued"
    finally:
        w.gcs.notify = real_notify
    # GCS reachable again: the retry must land the blob in the inline
    # table, so the value survives driver-cache eviction.
    for _ in range(200):
        lm._flush_reports()
        with lm._lock:
            if not lm._reports:
                break
        time.sleep(0.01)
    w._inline.pop(ref._id.binary())
    assert ray_tpu.get(ref, timeout=30) == "requeue-me"
