"""Cluster YAML launcher + process-backed node provider (reference:
``ray up`` / autoscaler commands.py; local node provider)."""

import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.autoscaler.cluster_launcher import (
    launch_cluster, load_cluster_config,
)


def test_yaml_launch_min_workers_and_autoscale(tmp_path):
    cfg_file = tmp_path / "cluster.yaml"
    cfg_file.write_text(textwrap.dedent("""
        cluster_name: t
        max_workers: 3
        idle_timeout_s: 300
        update_interval_s: 0.2
        provider:
          type: local_process
          object_store_memory: 67108864
        head_node_type:
          CPU: 1
        available_node_types:
          cpu_worker:
            resources: {CPU: 2}
            min_workers: 1
            max_workers: 3
    """))
    config = load_cluster_config(str(cfg_file))
    launched = launch_cluster(config)
    try:
        ray_tpu.init(address=launched.address)
        # min_workers=1: a second node (real OS process) joins the head.
        deadline = time.time() + 60
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.require_worker()
        while time.time() < deadline:
            if sum(1 for n in w.nodes() if n["Alive"]) >= 2:
                break
            time.sleep(0.2)
        assert sum(1 for n in w.nodes() if n["Alive"]) >= 2

        # Demand beyond current capacity scales up within max_workers.
        @ray_tpu.remote(num_cpus=2)
        def hold():
            time.sleep(3)
            return 1

        refs = [hold.remote() for _ in range(4)]
        assert ray_tpu.get(refs, timeout=120) == [1] * 4
        assert len(launched.provider.non_terminated_nodes()) >= 2
    finally:
        ray_tpu.shutdown()
        launched.shutdown()


def test_bad_yaml_rejected(tmp_path):
    bad = tmp_path / "bad.yaml"
    bad.write_text("cluster_name: x\n")
    with pytest.raises(ValueError):
        load_cluster_config(str(bad))
