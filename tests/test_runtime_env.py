"""Runtime environments: per-task/actor working_dir + py_modules shipped
through the GCS KV with content-addressed URI caching (reference:
_private/runtime_env/plugin.py:24 + packaging.py)."""

import os
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu._private.runtime_env import KV_NAMESPACE


@pytest.fixture
def ray_2cpu():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _make_module(tmp_path, name, body):
    mod = tmp_path / name
    mod.mkdir()
    (mod / "__init__.py").write_text(textwrap.dedent(body))
    return str(mod)


def test_py_modules_importable_in_task(ray_2cpu, tmp_path):
    mod = _make_module(tmp_path, "shiplib", """
        MAGIC = 1234

        def double(x):
            return 2 * x
    """)

    @ray_tpu.remote(runtime_env={"py_modules": [mod]})
    def use_module():
        import shiplib

        return shiplib.MAGIC, shiplib.double(21)

    assert ray_tpu.get(use_module.remote(), timeout=60) == (1234, 42)


def test_working_dir_sets_cwd(ray_2cpu, tmp_path):
    wd = tmp_path / "appdir"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-7")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def read_rel():
        with open("data.txt") as f:
            return f.read()

    assert ray_tpu.get(read_rel.remote(), timeout=60) == "payload-7"


def test_actor_runtime_env(ray_2cpu, tmp_path):
    mod = _make_module(tmp_path, "actorlib", """
        def greet(name):
            return f"hi {name}"
    """)
    wd = tmp_path / "actordir"
    wd.mkdir()
    (wd / "cfg.txt").write_text("cfgval")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [mod]})
    class Envy:
        def probe(self):
            import actorlib

            with open("cfg.txt") as f:
                return actorlib.greet(f.read())

    e = Envy.remote()
    assert ray_tpu.get(e.probe.remote(), timeout=60) == "hi cfgval"


def test_uri_cache_deduplicates(ray_2cpu, tmp_path):
    """The same content uploads once (content-addressed KV key) and the
    node extracts it once."""
    from ray_tpu._private import worker as worker_mod

    wd = tmp_path / "shared"
    wd.mkdir()
    (wd / "f.txt").write_text("same-bytes")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def probe():
        return sorted(os.listdir("."))

    assert ray_tpu.get(probe.remote(), timeout=60) == ["f.txt"]
    assert ray_tpu.get(probe.remote(), timeout=60) == ["f.txt"]

    kv = worker_mod.require_worker().kv()
    keys = kv.keys(namespace=KV_NAMESPACE)
    assert len(keys) == 1  # one content hash, uploaded once

    # The node's URI cache holds exactly one extraction for that hash.
    cluster = worker_mod._global_cluster
    cache = os.path.join(cluster.nm.session_dir, "runtime_resources")
    entries = [d for d in os.listdir(cache) if not d.startswith(".")]
    assert entries == [keys[0].decode()]


def test_env_vars_still_honored_with_working_dir(ray_2cpu, tmp_path):
    wd = tmp_path / "envdir"
    wd.mkdir()
    (wd / "x.txt").write_text("x")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "env_vars": {"SHIPPED_FLAG": "on"}})
    def probe():
        return os.environ.get("SHIPPED_FLAG"), os.path.exists("x.txt")

    assert ray_tpu.get(probe.remote(), timeout=60) == ("on", True)


def test_runtime_env_plugin_api(ray_2cpu):
    """The plugin seam (reference: runtime_env/plugin.py:24,116): a
    custom key is packaged driver-side and materialized node-side into
    worker env vars + sys.path — the mechanism conda/pip/container
    support plugs into."""
    import os

    import ray_tpu
    from ray_tpu._private import runtime_env as renv

    class StampPlugin(renv.RuntimeEnvPlugin):
        name = "stamp"

        def package(self, value, kv):
            return {"packaged": True, **value}

        def needs_isolation(self, value):
            return True

        def create(self, value, context, base_dir):
            assert value["packaged"]   # went through package()
            context["env_vars"]["RTPU_STAMP"] = value["tag"]
            d = os.path.join(base_dir, "stamp_dir")
            os.makedirs(d, exist_ok=True)
            context["py_paths"].append(d)

    renv.register_plugin(StampPlugin())
    try:
        @ray_tpu.remote(runtime_env={"stamp": {"tag": "hello-plugin"}})
        def read():
            import os
            import sys
            return (os.environ.get("RTPU_STAMP"),
                    any(p.endswith("stamp_dir") for p in sys.path))

        tag, on_path = ray_tpu.get(read.remote(), timeout=60)
        assert tag == "hello-plugin"
        assert on_path

        # Explicit env_vars beat plugin-provided ones.
        @ray_tpu.remote(runtime_env={"stamp": {"tag": "x"},
                                     "env_vars": {"RTPU_STAMP": "explicit"}})
        def read2():
            import os
            return os.environ.get("RTPU_STAMP")

        assert ray_tpu.get(read2.remote(), timeout=60) == "explicit"
    finally:
        renv.unregister_plugin("stamp")


def _make_wheel(tmp_path, name, version):
    """Hand-roll a minimal pure-python wheel (installable offline)."""
    import zipfile

    whl = tmp_path / f"{name}-{version}-py3-none-any.whl"
    dist = f"{name}-{version}.dist-info"
    with zipfile.ZipFile(whl, "w") as zf:
        zf.writestr(f"{name}/__init__.py",
                    f"__version__ = {version!r}\n")
        zf.writestr(f"{dist}/METADATA",
                    f"Metadata-Version: 2.1\nName: {name}\n"
                    f"Version: {version}\n")
        zf.writestr(f"{dist}/WHEEL",
                    "Wheel-Version: 1.0\nGenerator: test\nRoot-Is-"
                    "Purelib: true\nTag: py3-none-any\n")
        zf.writestr(f"{dist}/RECORD", "")
    return str(whl)


def test_pip_env_version_isolation(ray_2cpu, tmp_path):
    """Two CONCURRENT tasks with different pip envs import different
    versions of the same package (reference: runtime_env/pip.py venv per
    spec); a third task without the env sees no package at all."""
    whl1 = _make_wheel(tmp_path, "verpkg", "1.0")
    whl2 = _make_wheel(tmp_path, "verpkg", "2.0")

    @ray_tpu.remote(runtime_env={"pip": [whl1]})
    def v1():
        import verpkg
        return verpkg.__version__

    @ray_tpu.remote(runtime_env={"pip": [whl2]})
    def v2():
        import verpkg
        return verpkg.__version__

    @ray_tpu.remote
    def none():
        try:
            import verpkg  # noqa: F401
            return "leaked"
        except ImportError:
            return "clean"

    r1, r2, r3 = ray_tpu.get([v1.remote(), v2.remote(), none.remote()],
                             timeout=180)
    assert (r1, r2, r3) == ("1.0", "2.0", "clean")


def test_pip_env_venv_cached(ray_2cpu, tmp_path):
    """The same pip spec reuses its cached venv (one venv dir per hash)."""
    whl = _make_wheel(tmp_path, "cachepkg", "3.1")

    @ray_tpu.remote(runtime_env={"pip": [whl]})
    def use():
        import cachepkg
        return cachepkg.__version__

    assert ray_tpu.get(use.remote(), timeout=120) == "3.1"
    assert ray_tpu.get(use.remote(), timeout=120) == "3.1"
    from ray_tpu._private import worker as worker_mod

    session = worker_mod._global_cluster.session_dir
    pip_root = os.path.join(session, "runtime_resources", "pip")
    venvs = [d for d in os.listdir(pip_root)
             if os.path.isdir(os.path.join(pip_root, d))]
    assert len(venvs) == 1, venvs
