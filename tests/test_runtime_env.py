"""Runtime environments: per-task/actor working_dir + py_modules shipped
through the GCS KV with content-addressed URI caching (reference:
_private/runtime_env/plugin.py:24 + packaging.py)."""

import os
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu._private.runtime_env import KV_NAMESPACE


@pytest.fixture
def ray_2cpu():
    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def _make_module(tmp_path, name, body):
    mod = tmp_path / name
    mod.mkdir()
    (mod / "__init__.py").write_text(textwrap.dedent(body))
    return str(mod)


def test_py_modules_importable_in_task(ray_2cpu, tmp_path):
    mod = _make_module(tmp_path, "shiplib", """
        MAGIC = 1234

        def double(x):
            return 2 * x
    """)

    @ray_tpu.remote(runtime_env={"py_modules": [mod]})
    def use_module():
        import shiplib

        return shiplib.MAGIC, shiplib.double(21)

    assert ray_tpu.get(use_module.remote(), timeout=60) == (1234, 42)


def test_working_dir_sets_cwd(ray_2cpu, tmp_path):
    wd = tmp_path / "appdir"
    wd.mkdir()
    (wd / "data.txt").write_text("payload-7")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def read_rel():
        with open("data.txt") as f:
            return f.read()

    assert ray_tpu.get(read_rel.remote(), timeout=60) == "payload-7"


def test_actor_runtime_env(ray_2cpu, tmp_path):
    mod = _make_module(tmp_path, "actorlib", """
        def greet(name):
            return f"hi {name}"
    """)
    wd = tmp_path / "actordir"
    wd.mkdir()
    (wd / "cfg.txt").write_text("cfgval")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "py_modules": [mod]})
    class Envy:
        def probe(self):
            import actorlib

            with open("cfg.txt") as f:
                return actorlib.greet(f.read())

    e = Envy.remote()
    assert ray_tpu.get(e.probe.remote(), timeout=60) == "hi cfgval"


def test_uri_cache_deduplicates(ray_2cpu, tmp_path):
    """The same content uploads once (content-addressed KV key) and the
    node extracts it once."""
    from ray_tpu._private import worker as worker_mod

    wd = tmp_path / "shared"
    wd.mkdir()
    (wd / "f.txt").write_text("same-bytes")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd)})
    def probe():
        return sorted(os.listdir("."))

    assert ray_tpu.get(probe.remote(), timeout=60) == ["f.txt"]
    assert ray_tpu.get(probe.remote(), timeout=60) == ["f.txt"]

    kv = worker_mod.require_worker().kv()
    keys = kv.keys(namespace=KV_NAMESPACE)
    assert len(keys) == 1  # one content hash, uploaded once

    # The node's URI cache holds exactly one extraction for that hash.
    cluster = worker_mod._global_cluster
    cache = os.path.join(cluster.nm.session_dir, "runtime_resources")
    entries = [d for d in os.listdir(cache) if not d.startswith(".")]
    assert entries == [keys[0].decode()]


def test_env_vars_still_honored_with_working_dir(ray_2cpu, tmp_path):
    wd = tmp_path / "envdir"
    wd.mkdir()
    (wd / "x.txt").write_text("x")

    @ray_tpu.remote(runtime_env={"working_dir": str(wd),
                                 "env_vars": {"SHIPPED_FLAG": "on"}})
    def probe():
        return os.environ.get("SHIPPED_FLAG"), os.path.exists("x.txt")

    assert ray_tpu.get(probe.remote(), timeout=60) == ("on", True)
