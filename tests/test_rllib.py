"""RLlib slice tests: GAE math, learner update, end-to-end PPO learning
on CartPole with distributed rollout workers."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import PPOConfig, SampleBatch, concat_batches
from ray_tpu.rllib.sample_batch import compute_gae


def _cartpole():
    import gymnasium as gym

    return gym.make("CartPole-v1")


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_gae_simple():
    rewards = np.array([1.0, 1.0, 1.0], np.float32)
    values = np.zeros(3, np.float32)
    dones = np.array([False, False, True])
    adv, rets = compute_gae(rewards, values, dones, last_value=5.0,
                            gamma=1.0, lam=1.0)
    # terminal: no bootstrap; returns are reward-to-go
    np.testing.assert_allclose(rets, [3.0, 2.0, 1.0])

    adv2, rets2 = compute_gae(rewards, values,
                              np.array([False, False, False]),
                              last_value=5.0, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(rets2, [8.0, 7.0, 6.0])  # bootstrapped


def test_batch_ops():
    a = SampleBatch({"x": np.arange(4)})
    b = SampleBatch({"x": np.arange(4, 6)})
    c = concat_batches([a, b])
    assert c.count == 6
    mbs = list(c.minibatches(3))
    assert len(mbs) == 2 and mbs[0].count == 3
    sh = c.shuffle(np.random.default_rng(0))
    assert sorted(sh["x"]) == list(range(6))


def test_learner_reduces_loss():
    from ray_tpu.rllib import PPOLearner
    from ray_tpu.rllib.policy import PolicySpec
    from ray_tpu.rllib.sample_batch import (
        ACTIONS, ADVANTAGES, LOGPS, OBS, RETURNS,
    )

    spec = PolicySpec(obs_dim=4, num_actions=2)
    cfg = PPOConfig()
    learner = PPOLearner(spec, cfg)
    rng = np.random.default_rng(0)
    batch = SampleBatch({
        OBS: rng.normal(size=(256, 4)).astype(np.float32),
        ACTIONS: rng.integers(0, 2, 256).astype(np.int32),
        LOGPS: np.full(256, -0.69, np.float32),
        ADVANTAGES: rng.normal(size=256).astype(np.float32),
        RETURNS: rng.normal(size=256).astype(np.float32),
    })
    m1 = learner.update_from_batch(batch, num_epochs=1, minibatch_size=128,
                                   rng=rng)
    for _ in range(5):
        m2 = learner.update_from_batch(batch, num_epochs=1,
                                       minibatch_size=128, rng=rng)
    assert m2["vf_loss"] < m1["vf_loss"]


def test_ppo_cartpole_learns(ray_cluster):
    algo = (PPOConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=256)
            .training(num_sgd_epochs=4, sgd_minibatch_size=128, lr=1e-3)
            .build())
    first = algo.train()
    assert first["timesteps_this_iter"] == 512
    assert first["env_steps_per_sec"] > 0
    returns = []
    for _ in range(12):
        m = algo.train()
        if m["episode_return_mean"] is not None:
            returns.append(m["episode_return_mean"])
    algo.stop()
    # CartPole returns should clearly improve over ~13 iterations
    assert max(returns[-3:]) > returns[0] + 20, returns


def test_dqn_learner_reduces_td_error():
    """The jitted double-DQN update fits a fixed batch."""
    from ray_tpu.rllib import DQNConfig, DQNLearner, ReplayBuffer
    from ray_tpu.rllib.policy import PolicySpec

    rng = np.random.default_rng(0)
    spec = PolicySpec(obs_dim=4, num_actions=2)
    # gamma=0 makes the TD target the (fixed) reward — a supervised
    # regression whose loss must fall monotonically-ish.
    cfg = DQNConfig(lr=3e-3, gamma=0.0, target_update_freq=20)
    learner = DQNLearner(spec, cfg)
    buf = ReplayBuffer(1024, 4)
    obs = rng.normal(size=(512, 4)).astype(np.float32)
    acts = rng.integers(0, 2, 512)
    rews = (obs[np.arange(512), acts % 4] > 0).astype(np.float32)
    buf.add_batch(obs, acts, rews, obs, np.zeros(512, np.float32))

    m1 = learner.update_from_buffer(buf, iters=5, batch_size=128, rng=rng)
    for _ in range(20):
        m2 = learner.update_from_buffer(buf, iters=5, batch_size=128,
                                        rng=rng)
    assert m2["loss"] < m1["loss"]


def test_dqn_cartpole_improves(ray_cluster):
    """End-to-end DQN: epsilon-greedy rollout actors feeding the replay
    learner; the return trend must beat the random baseline."""
    from ray_tpu.rllib import DQNConfig

    algo = (DQNConfig()
            .environment(_cartpole)
            .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
            .training(lr=1e-3, learning_starts=400, num_sgd_iters=48,
                      train_batch_size=64, target_update_freq=100,
                      epsilon_decay_steps=3000, seed=0)
            .build())
    try:
        first = None
        for i in range(12):
            res = algo.train()
            if res["episode_return_mean"] is not None and first is None:
                first = res["episode_return_mean"]
        last = res["episode_return_mean"]
        assert res["timesteps_total"] >= 4000
        assert res["buffer_size"] > 1000
        assert res["epsilon"] < 0.5  # schedule advanced
        # CartPole random play scores ~20; learning should clearly beat it.
        assert last is not None and last > 40, (first, last)
    finally:
        algo.stop()


def test_learner_group_checkpoint_state(ray_cluster):
    """ADVICE r3: LearnerGroup must expose get_state/set_state so
    Algorithm.save/restore_checkpoint works with num_learners > 1."""
    from ray_tpu.rllib.learner_group import LearnerGroup
    from ray_tpu.rllib.ppo import PPOLearner
    from ray_tpu.rllib.policy import PolicySpec

    spec = PolicySpec(obs_dim=4, num_actions=2)
    cfg = PPOConfig()
    group = LearnerGroup(lambda: PPOLearner(spec, cfg), num_learners=2)
    try:
        state = group.get_state()
        assert "params" in state and "opt_state" in state
        group.set_state(state)   # broadcast restores every shard
        w0 = group.get_weights()
        import jax
        jax.tree.map(np.testing.assert_allclose, w0, state["params"])
    finally:
        group.stop()


def test_a2c_microbatch_single_optimizer_step():
    """ADVICE r3: microbatched A2C must accumulate grads and take ONE
    optimizer step per train batch (not one per microbatch): the Adam
    step counter advances by exactly 1 and params match the full-batch
    update to fp-accumulation tolerance (advantages are normalized once
    over the full train batch, so equivalence is exact in real math)."""
    from ray_tpu.rllib.a2c import A2CConfig, A2CLearner
    from ray_tpu.rllib.policy import PolicySpec
    from ray_tpu.rllib.sample_batch import (
        ACTIONS, ADVANTAGES, OBS, RETURNS,
    )
    import jax
    import optax

    spec = PolicySpec(obs_dim=4, num_actions=2)
    cfg = A2CConfig(seed=0)
    rng = np.random.default_rng(0)
    batch = SampleBatch({
        OBS: rng.normal(size=(96, 4)).astype(np.float32),
        ACTIONS: rng.integers(0, 2, 96).astype(np.int32),
        ADVANTAGES: rng.normal(size=96).astype(np.float32),
        RETURNS: rng.normal(size=96).astype(np.float32),
    })
    full = A2CLearner(spec, cfg)
    micro = A2CLearner(spec, cfg)
    micro.set_state(jax.tree.map(lambda x: x, full.get_state()))

    full.update_from_batch(batch, microbatch_size=0)
    m = micro.update_from_batch(batch, microbatch_size=32)
    assert isinstance(m, dict) and "policy_loss" in m

    steps = [int(c) for c in jax.tree.leaves(
        jax.tree.map(lambda x: x, micro.get_state()["opt_state"]))
        if np.ndim(c) == 0 and np.issubdtype(np.asarray(c).dtype, np.integer)]
    assert steps and all(s == 1 for s in steps), steps
    diffs = jax.tree.map(lambda a, b: float(np.max(np.abs(a - b))),
                         full.get_weights(), micro.get_weights())
    assert max(jax.tree.leaves(diffs)) < 1e-4, diffs
