"""Test configuration.

JAX must run on a virtual 8-device CPU mesh for all tests (the TPU tunnel is
single-chip; sharding tests need a mesh), so set the platform flags before
jax is ever imported.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()
