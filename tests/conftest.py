"""Test configuration.

JAX must run on a virtual 8-device CPU mesh for all tests (the TPU tunnel is
single-chip; sharding tests need a mesh). The environment pre-imports jax via
a sitecustomize hook, so env vars set here are too late for jax's import-time
config read — instead we switch the platform with ``jax.config.update`` before
any backend is initialized, which jax honors until first device use.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Subprocesses (workers) read these at interpreter start.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (chaos sweeps); excluded from tier-1 "
        "via -m 'not slow'")


# Runtime lock-order witness (ray_tpu._private.lockdep): enabled for the
# scheduler / gang / device-object modules — the control-plane surfaces
# whose lock graphs raylint's static lock-order checker models. Once a
# test from these modules installs it, it stays on for the rest of the
# session (wrapping is creation-time, so coverage only grows); every
# test teardown then asserts no ordering cycle was witnessed.
LOCKDEP_MODULES = {
    "test_local_scheduler",
    "test_gang_fault_tolerance",
    "test_device_objects",
    "test_serve_llm",
    # The GCS shard locks (sched/actor/obj/kv) carry a canonical rank
    # order; these two modules drive the scale and fault-tolerance paths
    # that exercise every cross-shard protocol, so the runtime witness
    # asserts no rank inversion ever executes.
    "test_scheduler_scale",
    "test_gcs_fault_tolerance",
    "test_actor_leases",
    # Static<->runtime lock-graph reconciliation needs the runtime
    # witness recording while it drives the init/task/actor workload.
    "test_lockgraph_reconcile",
    # The profiler's sampler/window/table locks run inside every
    # process the cluster owns (and its fan-in crosses the NM/GCS agent
    # paths) — witness its lock graph wherever its tests drive it.
    "test_profiler",
    # The submit fast path adds the classic-batch buffer lock, the ring
    # writer lock, and the NM's ring-drain thread to the lease/NM/GCS
    # lock graph — witness the new blocking edges where they are driven.
    "test_submit_fastpath",
    # The result-return fast path adds the inline table/cache leaf
    # locks, the worker's completion-buffer lock, and the GCS's batched
    # completion handler to that same graph — witness it end to end.
    "test_inline_returns",
    # The completion-ingestion fast path adds the absorb executor, the
    # completion-ring producer lock (held on the NM's task_done path),
    # caller-thread steal-absorb, and the worker-segment edges — the
    # driver's _comp_ring_lock around the segment registry (taken from
    # lease conn threads, the consumer loop, AND the lease failure
    # path's bounded drain-wait) plus the worker's producer lock — to
    # the lease/NM lock graph. Witness the edges where its tests drive
    # them.
    "test_completion_fastpath",
    # Prefix caching shares refcounted KV blocks across slots under the
    # engine's admission lock while the scheduler thread allocates,
    # registers and releases them — witness the engine/pool lock edges
    # the sharing adds (admission, preemption, cancel, disagg adopt).
    "test_prefix_cache",
}


def _lockdep_env_enabled() -> bool:
    # Same truthiness vocabulary as the config registry's bool coercion:
    # RAY_TPU_LOCKDEP_ENABLED=0 must mean OFF, not "set, therefore on".
    return os.environ.get("RAY_TPU_LOCKDEP_ENABLED", "").lower() in (
        "1", "true", "yes", "on")


def pytest_runtest_setup(item):
    mod = getattr(item.module, "__name__", "")
    lockdep_on = mod in LOCKDEP_MODULES or _lockdep_env_enabled()
    if lockdep_on:
        from ray_tpu._private import lockdep

        lockdep.install()
    # Out-of-process control-plane children spawned by lockdep-module
    # tests (the `python -m ray_tpu._private.gcs` entrypoint) run
    # lockdep too: the knob rides the launcher's --system-config diff,
    # and the entrypoint exits rc=3 if its serve/shutdown path witnessed
    # an ordering cycle. The knob is re-set per test (the registry is
    # process-global) so children of NON-lockdep tests don't inherit it
    # — in-process install stays session-sticky by design, but child
    # semantics must not leak across modules.
    from ray_tpu._private.config import config

    config.set("lockdep_enabled", lockdep_on)


@pytest.fixture(autouse=True)
def _lockdep_cycle_guard():
    """Assert no lock-order cycle was witnessed during the test. A
    fixture finalizer (NOT a raising pytest_runtest_teardown hook): a
    hook exception aborts the SetupState unwind and poisons the NEXT
    test's setup with 'previous item was not torn down properly'."""
    yield
    from ray_tpu._private import lockdep

    if lockdep.installed():
        found = lockdep.take_violations()
        if found:
            pytest.fail(
                "lockdep witnessed a lock-order cycle during this test:\n"
                + "\n".join(str(v) for v in found), pytrace=False)


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """The 8 virtual CPU devices standing in for one TPU slice."""
    return jax.devices("cpu")
