"""Test configuration.

JAX must run on a virtual 8-device CPU mesh for all tests (the TPU tunnel is
single-chip; sharding tests need a mesh). The environment pre-imports jax via
a sitecustomize hook, so env vars set here are too late for jax's import-time
config read — instead we switch the platform with ``jax.config.update`` before
any backend is initialized, which jax honors until first device use.
"""

import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Subprocesses (workers) read these at interpreter start.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (chaos sweeps); excluded from tier-1 "
        "via -m 'not slow'")


@pytest.fixture
def ray_start_regular():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_cpus():
    import ray_tpu

    ctx = ray_tpu.init(num_cpus=2, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


@pytest.fixture(scope="session")
def cpu_mesh_devices():
    """The 8 virtual CPU devices standing in for one TPU slice."""
    return jax.devices("cpu")
