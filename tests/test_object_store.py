import multiprocessing
import os
import threading
import time

import numpy as np
import pytest

from ray_tpu.object_store import plasma


@pytest.fixture
def store(tmp_path):
    path = str(tmp_path / "arena")
    plasma.create_store(path, capacity=64 * 1024 * 1024, max_objects=1024)
    client = plasma.PlasmaClient(path)
    yield client
    client.close()


def oid(i: int) -> bytes:
    return i.to_bytes(4, "little") + b"\x00" * 24


def test_create_seal_get(store):
    buf = store.create(oid(1), 5)
    buf[:] = b"hello"
    del buf
    store.seal(oid(1))
    view = store.get_buffer(oid(1), timeout_ms=0)
    assert bytes(view) == b"hello"
    del view
    store.release(oid(1))
    assert store.contains(oid(1))


def test_get_missing_nonblocking(store):
    assert store.get_buffer(oid(99), timeout_ms=0) is None


def test_get_timeout(store):
    t0 = time.monotonic()
    assert store.get_buffer(oid(98), timeout_ms=100) is None
    assert time.monotonic() - t0 >= 0.09


def test_seal_wakes_getter(store):
    result = {}

    def getter():
        v = store.get_buffer(oid(5), timeout_ms=5000)
        result["data"] = bytes(v) if v else None
        if v is not None:
            del v
            store.release(oid(5))

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.05)
    buf = store.create(oid(5), 3)
    buf[:] = b"abc"
    del buf
    store.seal(oid(5))
    t.join(timeout=5)
    assert result["data"] == b"abc"


def test_value_roundtrip(store):
    arr = np.arange(10000, dtype=np.float32)
    store.put_value(oid(7), {"arr": arr, "n": 3})
    val, ok = store.get_value(oid(7), timeout_ms=0)
    assert ok
    np.testing.assert_array_equal(val["arr"], arr)
    assert val["n"] == 3


def test_delete_and_exists(store):
    store.put_value(oid(8), "x")
    with pytest.raises(plasma.ObjectExistsError):
        store.create(oid(8), 4)
    assert store.delete(oid(8))
    assert not store.contains(oid(8))


def test_lru_eviction(tmp_path):
    path = str(tmp_path / "small")
    plasma.create_store(path, capacity=1024 * 1024, max_objects=64)
    c = plasma.PlasmaClient(path)
    # Fill beyond capacity; old sealed unpinned objects must be evicted.
    for i in range(10):
        buf = c.create(oid(i), 200 * 1024)
        del buf
        c.seal(oid(i))
    stats = c.stats()
    assert stats["evictions"] > 0
    assert c.contains(oid(9))  # newest survives
    assert not c.contains(oid(0))  # oldest evicted
    c.close()


def test_pinned_objects_not_evicted(tmp_path):
    path = str(tmp_path / "pin")
    plasma.create_store(path, capacity=1024 * 1024, max_objects=64)
    c = plasma.PlasmaClient(path)
    buf = c.create(oid(0), 300 * 1024)
    del buf
    c.seal(oid(0))
    view = c.get_buffer(oid(0), timeout_ms=0)  # pin it
    for i in range(1, 8):
        b = c.create(oid(i), 200 * 1024)
        del b
        c.seal(oid(i))
    assert c.contains(oid(0))  # pinned despite pressure
    del view
    c.release(oid(0))
    c.close()


def test_oom_when_all_pinned(tmp_path):
    path = str(tmp_path / "oom")
    plasma.create_store(path, capacity=512 * 1024, max_objects=64)
    c = plasma.PlasmaClient(path)
    buf = c.create(oid(0), 400 * 1024)  # unsealed = pinned by creator
    with pytest.raises(plasma.StoreFullError):
        c.create(oid(1), 400 * 1024)
    del buf
    c.abort(oid(0))
    b2 = c.create(oid(1), 400 * 1024)  # now fits
    del b2
    c.close()


def _child_put(path: str):
    c = plasma.PlasmaClient(path)
    c.put_value(b"B" * 28, np.arange(1000))
    c.close()


def test_cross_process_sharing(tmp_path):
    path = str(tmp_path / "xproc")
    plasma.create_store(path, capacity=8 * 1024 * 1024, max_objects=256)
    c = plasma.PlasmaClient(path)
    ctx = multiprocessing.get_context("fork")
    p = ctx.Process(target=_child_put, args=(path,))
    p.start()
    val, ok = c.get_value(b"B" * 28, timeout_ms=10000)
    p.join()
    assert ok
    np.testing.assert_array_equal(val, np.arange(1000))
    c.close()


def test_stats(store):
    s0 = store.stats()
    store.put_value(oid(40), b"x" * 1000)
    s1 = store.stats()
    assert s1["num_objects"] == s0["num_objects"] + 1
    assert s1["used_bytes"] > s0["used_bytes"]
