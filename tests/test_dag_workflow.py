"""DAG API and durable-workflow tests."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture(scope="module")
def ray_cluster():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_dag_basic(ray_cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    with InputNode() as x:
        dag = add.bind(mul.bind(x, 2), mul.bind(x, 3))
    ref = dag.execute(5)
    assert ray_tpu.get(ref) == 25  # 5*2 + 5*3


def test_dag_diamond_single_execution(ray_cluster):
    """A shared upstream node must submit exactly once."""
    import tempfile

    count_file = os.path.join(tempfile.mkdtemp(), "count")

    @ray_tpu.remote
    def once():
        with open(count_file, "a") as f:
            f.write("x")
        return 1

    @ray_tpu.remote
    def add(a, b):
        return a + b

    shared = once.bind()
    dag = add.bind(shared, shared)
    assert ray_tpu.get(dag.execute()) == 2
    assert os.path.getsize(count_file) == 1


def test_actor_method_bind(ray_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def incr(self, by):
            self.v += by
            return self.v

    c = Counter.remote()
    dag = c.incr.bind(5)
    assert ray_tpu.get(dag.execute()) == 5


def test_workflow_run_and_output(ray_cluster, tmp_path):
    workflow.init(str(tmp_path))

    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as x:
        dag = add.bind(double.bind(x), 10)
    out = workflow.run(dag, workflow_id="wf1", args=(7,))
    assert out == 24
    assert workflow.get_status("wf1") == "SUCCESSFUL"
    assert workflow.get_output("wf1") == 24
    assert ("wf1", "SUCCESSFUL") in workflow.list_all()


def test_workflow_resume_skips_completed_steps(ray_cluster, tmp_path):
    workflow.init(str(tmp_path))
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)

    @ray_tpu.remote
    def step_a():
        open(os.path.join(marker_dir, "a"), "a").write("x")
        return 5

    @ray_tpu.remote
    def step_b(v):
        # fails the first time only
        flag = os.path.join(marker_dir, "b_failed")
        if not os.path.exists(flag):
            open(flag, "w").write("x")
            raise RuntimeError("transient failure")
        return v * 3

    dag = step_b.bind(step_a.bind())
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf2")
    assert workflow.get_status("wf2") == "FAILED"

    out = workflow.resume("wf2")
    assert out == 15
    assert workflow.get_status("wf2") == "SUCCESSFUL"
    # step_a executed exactly once (checkpoint reused on resume)
    assert os.path.getsize(os.path.join(marker_dir, "a")) == 1
