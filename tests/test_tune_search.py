"""Model-based search (Searcher seam + native TPE) and HyperBand
(reference: tune/search/searcher.py, tune/search/hyperopt/
hyperopt_search.py, tune/schedulers/hyperband.py:40)."""

import random

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.tune import (
    HyperBandScheduler, TPESearcher, TuneConfig, Tuner,
)
from ray_tpu.tune.schedulers import CONTINUE, STOP


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


def test_tpe_concentrates_on_good_region():
    """Unit: fed observations with a clear optimum, TPE's suggestions
    cluster near it (no cluster needed)."""
    s = TPESearcher(metric="loss", mode="min", n_initial=0, seed=7)
    s.set_search_properties("loss", "min",
                            {"x": tune.uniform(0.0, 1.0),
                             "c": tune.choice(["a", "b", "c"])})
    rng = random.Random(0)
    for i in range(30):
        x = rng.uniform(0, 1)
        c = rng.choice(["a", "b", "c"])
        # optimum at x=0.8, category "b"
        loss = (x - 0.8) ** 2 + (0.0 if c == "b" else 0.3)
        tid = f"t{i}"
        s._suggested[tid] = {("x",): x, ("c",): c}
        s.on_trial_complete(tid, {"loss": loss})
    xs, cs = [], []
    for i in range(40):
        cfg = s.suggest(f"s{i}")
        xs.append(cfg["x"])
        cs.append(cfg["c"])
    near = sum(1 for x in xs if abs(x - 0.8) < 0.25)
    assert near >= 28, (near, sorted(xs)[:5])
    assert cs.count("b") >= 24, cs.count("b")


def _bowl(config):
    x, y = config["x"], config["y"]
    tune.report({"loss": (x - 0.2) ** 2 + (y + 0.4) ** 2})


def test_tpe_beats_random_within_budget():
    """Seeded convergence, 10 paired seeds: on a smooth bowl, TPE's
    best-of-24 beats random search's best-of-24 in >= 8/10 runs (a
    single paired seed is a coin flip when random gets lucky; the
    reference promise of model-based search is the distribution)."""
    def f(cfg):
        return (cfg["x"] - 0.2) ** 2 + (cfg["y"] + 0.4) ** 2

    space = {"x": tune.uniform(-1.0, 1.0), "y": tune.uniform(-1.0, 1.0)}
    wins = 0
    for seed in range(10):
        s = TPESearcher(metric="loss", mode="min", n_initial=6, seed=seed)
        s.set_search_properties("loss", "min", space)
        best_tpe = float("inf")
        for i in range(24):
            cfg = s.suggest(f"t{i}")
            v = f(cfg)
            s.on_trial_complete(f"t{i}", {"loss": v})
            best_tpe = min(best_tpe, v)
        rng = random.Random(1000 + seed)
        best_rand = min(f({"x": rng.uniform(-1, 1),
                           "y": rng.uniform(-1, 1)})
                        for _ in range(24))
        wins += best_tpe < best_rand
    assert wins >= 8, wins


def test_tpe_drives_tuner_end_to_end(ray_4cpu):
    """TPE through the full Tuner loop (configs suggested at launch,
    completions fed back) reaches the bowl's floor."""
    searcher = TPESearcher(metric="loss", mode="min", n_initial=5, seed=0)
    grid = Tuner(
        _bowl,
        param_space={"x": tune.uniform(-1.0, 1.0),
                     "y": tune.uniform(-1.0, 1.0)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=16,
            max_concurrent_trials=2, search_alg=searcher),
    ).fit()
    assert len(grid) == 16
    assert len(searcher._obs) == 16   # every completion observed
    assert grid.get_best_result().metrics["loss"] < 0.05


def test_tpe_composes_with_asha(ray_4cpu):
    """Searcher + scheduler: ASHA prunes mid-trial while TPE keeps
    learning from (possibly pruned) completions."""
    def train_fn(config):
        m = config["m"]
        for i in range(8):
            tune.report({"loss": (m - 0.5) ** 2 + 1.0 / (i + 1)})

    searcher = TPESearcher(metric="loss", mode="min", n_initial=4, seed=1)
    grid = Tuner(
        train_fn, param_space={"m": tune.uniform(0.0, 1.0)},
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=10,
            max_concurrent_trials=2, search_alg=searcher,
            scheduler=tune.ASHAScheduler(
                metric="loss", mode="min", max_t=8, grace_period=2)),
    ).fit()
    assert len(grid) == 10
    assert len(searcher._obs) >= 5   # completions (incl. pruned) observed
    assert grid.get_best_result().metrics["loss"] < 0.5


def test_hyperband_brackets_and_stopping():
    """Unit: bracket assignment round-robins; a clearly-worst trial in a
    small-grace bracket is stopped at its first rung while the best
    continues to max_t."""
    hb = HyperBandScheduler(metric="loss", mode="min", max_t=9,
                            reduction_factor=3)
    assert len(hb._brackets) == 3
    for i in range(6):
        hb.on_trial_add(f"t{i}", {})
    assert hb._assignment["t0"] != hb._assignment["t1"] or \
        len(hb._brackets) == 1
    # Bracket 0 has grace 1: feed 3 trials at t=1, worst must stop.
    b0 = [tid for tid, b in hb._assignment.items() if b == 0][:3]
    while len(b0) < 3:
        tid = f"x{len(b0)}"
        hb._assignment[tid] = 0
        b0.append(tid)
    decisions = {}
    for rank, tid in enumerate(b0):
        decisions[tid] = hb.on_result(
            tid, {"training_iteration": 1, "loss": float(rank)})
    assert decisions[b0[2]] == STOP          # worst of the rung
    assert decisions[b0[0]] == CONTINUE      # best survives
    assert hb.on_result(b0[0], {"training_iteration": 9,
                                "loss": 0.0}) == STOP   # max_t reached


def test_hyperband_in_tuner(ray_4cpu):
    def train_fn(config):
        for i in range(9):
            tune.report({"loss": config["m"] + 1.0 / (i + 1)})

    grid = Tuner(
        train_fn,
        param_space={"m": tune.grid_search([0.1 * i for i in range(6)])},
        tune_config=TuneConfig(
            metric="loss", mode="min",
            scheduler=HyperBandScheduler(metric="loss", mode="min",
                                         max_t=9, reduction_factor=3)),
    ).fit()
    states = {t.state for t in grid._trials}
    assert states <= {"TERMINATED", "STOPPED"}
    assert grid.get_best_result().metrics["loss"] < 0.35
