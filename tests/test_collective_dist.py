"""Cross-process XLA collective group (xla_dist backend).

Each rank is a separate worker-actor process; the ranks rendezvous a
jax.distributed world through the group's named coordinator actor and run
dense collectives as single compiled XLA programs spanning the processes
(reference parity target:
``util/collective/collective_group/nccl_collective_group.py:127``).
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def ray_4cpu():
    ctx = ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    yield ctx
    ray_tpu.shutdown()


class _DistWorker:
    """One rank of an xla_dist group; joins in __init__-free style so the
    group forms inside the concurrently-running method calls."""

    def join(self, world, rank, name):
        from ray_tpu.parallel import collective

        self._g = collective.init_collective_group(
            world, rank, backend="xla_dist", group_name=name)
        return True

    def world_info(self):
        import jax

        return {"process_count": jax.process_count(),
                "process_index": jax.process_index(),
                "mesh_devices": int(np.prod(self._g.mesh.devices.shape))}

    def allreduce(self, value, shape=(8,)):
        out = self._g.allreduce(np.full(shape, value, np.float32))
        return np.asarray(out).tolist()

    def allgather(self, value):
        return np.asarray(
            self._g.allgather(np.full((2,), value, np.float32))).tolist()

    def broadcast(self, rank):
        payload = (np.arange(4, dtype=np.float32) if rank == 0
                   else np.zeros(4, np.float32))
        return np.asarray(self._g.broadcast(payload, src_rank=0)).tolist()

    def reducescatter(self, rank, world):
        t = np.full((2 * world, 3), float(rank + 1), np.float32)
        return np.asarray(self._g.reducescatter(t)).tolist()

    def p2p(self, rank):
        if rank == 0:
            self._g.send(np.full((4,), 7.0, np.float32), dst_rank=1)
            return None
        return np.asarray(
            self._g.recv((4,), np.float32, src_rank=0)).tolist()

    def barrier(self):
        self._g.barrier()
        return True


def test_xla_dist_group(ray_4cpu):
    """Two worker processes form one jax.distributed world; every dense
    collective is a compiled XLA program spanning both."""
    world = 2
    cls = ray_tpu.remote(_DistWorker)
    workers = [cls.remote() for _ in range(world)]
    assert ray_tpu.get(
        [w.join.remote(world, r, "tdist") for r, w in enumerate(workers)],
        timeout=180) == [True, True]

    # The world genuinely spans the two actor processes.
    infos = ray_tpu.get([w.world_info.remote() for w in workers])
    assert [i["process_count"] for i in infos] == [2, 2]
    assert sorted(i["process_index"] for i in infos) == [0, 1]
    assert all(i["mesh_devices"] == 2 for i in infos)

    # allreduce: sum of (rank+1)-filled tensors = 3.0 everywhere
    outs = ray_tpu.get(
        [w.allreduce.remote(float(r + 1)) for r, w in enumerate(workers)],
        timeout=120)
    for o in outs:
        assert o == [3.0] * 8

    # allgather: rank-major stack visible on every rank
    outs = ray_tpu.get(
        [w.allgather.remote(float(r)) for r, w in enumerate(workers)],
        timeout=120)
    for o in outs:
        assert o == [[0.0, 0.0], [1.0, 1.0]]

    # broadcast from rank 0
    outs = ray_tpu.get(
        [w.broadcast.remote(r) for r, w in enumerate(workers)], timeout=120)
    for o in outs:
        assert o == [0.0, 1.0, 2.0, 3.0]

    # reducescatter: each rank gets its chunk of the summed tensor
    outs = ray_tpu.get(
        [w.reducescatter.remote(r, world) for r, w in enumerate(workers)],
        timeout=120)
    for o in outs:
        assert np.allclose(np.asarray(o), 3.0)
        assert np.asarray(o).shape == (2, 3)

    # p2p rides the coordinator mailbox
    outs = ray_tpu.get(
        [w.p2p.remote(r) for r, w in enumerate(workers)], timeout=120)
    assert outs[1] == [7.0] * 4

    assert ray_tpu.get([w.barrier.remote() for w in workers],
                       timeout=120) == [True, True]


def _xla_dist_train_loop(config):
    """JaxTrainer loop whose gradient allreduce goes through the compiled
    cross-process XLA collective (the trainer's default backend)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu import train
    from ray_tpu.parallel import collective

    sess_group = train.session._get_session().collective_group_name
    g = collective.get_group(sess_group)
    # The group must be the multi-controller XLA kind, not the store poller.
    assert type(g).__name__ == "XlaDistributedGroup"
    assert jax.process_count() == train.get_world_size()

    rank, ws = train.get_world_rank(), train.get_world_size()
    w = jnp.zeros((4,), jnp.float32)
    rng = np.random.default_rng(rank)
    x = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)

    for step in range(config["steps"]):
        grad = jax.grad(lambda w: jnp.mean((x @ w - 1.0) ** 2))(w)
        grad = jnp.asarray(g.allreduce(np.asarray(grad))) / ws
        w = w - 0.1 * grad
        if rank == 0:
            train.report({"step": step, "loss": float(
                jnp.mean((x @ w - 1.0) ** 2))})


def test_jax_trainer_uses_xla_dist(ray_4cpu, tmp_path):
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    trainer = JaxTrainer(
        _xla_dist_train_loop,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="xd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.ok, result.error
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]
